"""distrisched: the deterministic schedule-exploration harness
(distrifuser_tpu/analysis/concurrency/) demonstrably detects seeded
races and deadlocks (negative controls — the gate cannot be vacuous),
their lock-fixed twins pass clean, a seed replays byte-identically, the
serve scenario suite holds its invariants across seeds, and the
sync-containment checker fences the instrumentable layer.
"""

import ast
import os
import subprocess
import sys

import pytest

from distrifuser_tpu.analysis.checkers import sync_containment
from distrifuser_tpu.analysis.checkers.lock_discipline import (
    GUARDED_REGISTRY,
)
from distrifuser_tpu.analysis.concurrency import (
    DEADLOCK,
    RACE,
    SCENARIOS,
    explore,
    run_schedule,
    synthesize_findings,
)
from distrifuser_tpu.analysis.concurrency.harness import (
    _registry_coverage,
)
from distrifuser_tpu.utils import sync

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# fixtures: a deliberately racy class, its lock-fixed twin, AB/BA locks


class RacyCounter:
    """Unsynchronized read-modify-write from two threads — the race the
    detector MUST flag."""

    def __init__(self):
        self.value = 0

    def bump(self, rounds: int = 3) -> None:
        for _ in range(rounds):
            v = self.value
            self.value = v + 1


class LockedCounter:
    """The lock-fixed twin: identical shape, mutations under the lock."""

    def __init__(self):
        self._lock = sync.Lock()
        self.value = 0

    def bump(self, rounds: int = 3) -> None:
        for _ in range(rounds):
            with self._lock:
                self.value += 1


def _two_bumpers(counter_cls):
    def scenario(ctx):
        c = counter_cls()
        t1 = ctx.spawn("w1", c.bump)
        t2 = ctx.spawn("w2", c.bump)
        t1.join()
        t2.join()

    return scenario


def _ab_ba_scenario(ctx):
    a = sync.Lock()
    b = sync.Lock()

    def ab():
        with a:
            ctx.rt.yield_point("between-ab")
            with b:
                pass

    def ba():
        with b:
            ctx.rt.yield_point("between-ba")
            with a:
                pass

    t1 = ctx.spawn("ab", ab)
    t2 = ctx.spawn("ba", ba)
    t1.join()
    t2.join()


def _ordered_scenario(ctx):
    """The deadlock fixture's fixed twin: one global lock order."""
    a = sync.Lock()
    b = sync.Lock()

    def worker(name):
        with a:
            ctx.rt.yield_point(f"between-{name}")
            with b:
                pass

    t1 = ctx.spawn("w1", worker, "w1")
    t2 = ctx.spawn("w2", worker, "w2")
    t1.join()
    t2.join()


# ---------------------------------------------------------------------------
# negative controls: the detectors demonstrably fire


def test_racy_fixture_is_flagged():
    results = [run_schedule(_two_bumpers(RacyCounter), seed, name="racy",
                            extra_classes=(RacyCounter,))
               for seed in range(3)]
    assert all(r.error is None for r in results), [r.error for r in results]
    findings = synthesize_findings(results, extra_classes=(RacyCounter,))
    races = [f for f in findings if f.checker == RACE]
    assert races, "the unsynchronized counter must be flagged"
    assert any("RacyCounter.value" in f.message and "write-write" in
               f.message for f in races)


def test_lock_fixed_twin_is_clean():
    results = [run_schedule(_two_bumpers(LockedCounter), seed,
                            name="locked", extra_classes=(LockedCounter,))
               for seed in range(5)]
    assert all(r.error is None for r in results)
    findings = synthesize_findings(results,
                                   extra_classes=(LockedCounter,))
    assert [f for f in findings if f.checker == RACE] == [], [
        f.render() for f in findings]


def test_read_write_race_needs_check_reads():
    """Read/write pairs are reported only in fixture (check_reads) mode:
    the shipped gate runs writes-only, mirroring the repo's blessed
    snapshot-read thread model."""

    class Holder:
        def __init__(self):
            self.cell = 0

    def scenario(ctx):
        h = Holder()

        def writer():
            h.cell = 1

        def reader():
            _ = h.cell

        t1 = ctx.spawn("writer", writer)
        t2 = ctx.spawn("reader", reader)
        t1.join()
        t2.join()

    kinds = set()
    for seed in range(4):
        r = run_schedule(scenario, seed, name="rw", check_reads=True,
                         extra_classes=(Holder,))
        kinds.update(rep.kind for rep in r.race_reports)
    assert kinds & {"read-write", "write-read"}, kinds
    r = run_schedule(scenario, 0, name="rw-off", check_reads=False,
                     extra_classes=(Holder,))
    assert r.race_reports == []


def test_ab_ba_deadlock_fixture_is_flagged():
    """The AB/BA fixture must produce a deadlock finding across a small
    seed sweep — as a concretely wedged schedule (with its wait-for
    cycle) and/or as a lock-order cycle accumulated from the schedules
    that got lucky."""
    results = [run_schedule(_ab_ba_scenario, seed, name="abba")
               for seed in range(10)]
    findings = synthesize_findings(results)
    dl = [f for f in findings if f.checker == DEADLOCK]
    assert dl, "AB/BA lock order went undetected"
    # the lock-order union across schedules must see the cycle even when
    # no single schedule wedged
    assert any("cycle" in f.identity or "wedge" in f.identity
               for f in dl)
    # wedged schedules abort and report — never hang the harness — and
    # an injected FAILURE replays byte-identically from its seed
    for r in results:
        if r.deadlocks:
            assert "DEADLOCK" in r.trace
            again = run_schedule(_ab_ba_scenario, r.seed, name="abba")
            assert again.trace == r.trace
            break


def test_ordered_twin_is_clean():
    results = [run_schedule(_ordered_scenario, seed, name="ordered")
               for seed in range(10)]
    assert all(r.error is None for r in results)
    findings = synthesize_findings(results)
    assert [f for f in findings if f.checker == DEADLOCK] == []


def test_drift_recorder_sees_multi_writer_attrs():
    """The write-origin recorder (the guard-registry drift feed) counts
    distinct writer threads per object attr — locking does not matter,
    registry membership does."""
    r = run_schedule(_two_bumpers(LockedCounter), 0, name="drift",
                     extra_classes=(LockedCounter,))
    assert ("LockedCounter", "value") in r.writes.multi_writer_attrs()


# ---------------------------------------------------------------------------
# determinism: same seed => byte-identical schedule trace and findings


def test_seed_replay_is_byte_identical():
    for scenario in ("submit_stop_race", "failover_exactly_once"):
        a = run_schedule(SCENARIOS[scenario], 11, name=scenario)
        b = run_schedule(SCENARIOS[scenario], 11, name=scenario)
        assert a.error is None and b.error is None, (a.error, b.error)
        assert a.trace == b.trace, f"{scenario}: schedule not replayable"
        fa = [f.fingerprint for f in synthesize_findings([a])]
        fb = [f.fingerprint for f in synthesize_findings([b])]
        assert fa == fb


def test_seeds_explore_distinct_schedules():
    traces = {run_schedule(SCENARIOS["submit_stop_race"], seed,
                           name="s").trace for seed in range(8)}
    assert len(traces) > 1, "every seed produced the same interleaving"


# ---------------------------------------------------------------------------
# the tier-1 gate: the serve scenario suite holds across seeds


def test_serve_scenarios_clean_under_exploration():
    """A slice of the CI gate (which runs 50 seeds per scenario): every
    scenario x seed is failure-free and the shipped tree yields zero
    race/deadlock/drift findings."""
    res = explore(SCENARIOS, range(6))
    assert res.schedules_explored == 6 * len(SCENARIOS)
    assert res.failures == [], [
        (f.scenario, f.seed, f.error) for f in res.failures]
    assert res.findings == [], [f.render() for f in res.findings]


def test_scenario_suite_covers_the_issue_catalog():
    assert set(SCENARIOS) == {
        "submit_stop_race", "failover_exactly_once",
        "drain_completes_inflight", "kill_restart_generation",
        "staging_stop_midpipeline",
        # ISSUE 15: step-level continuous batching
        "stepbatch_join_while_stepping", "stepbatch_preempt_cancel_race",
        "stepbatch_stop_midpreview",
        # ISSUE 16: distrigate HTTP/SSE gateway
        "gateway_stop_midstream", "gateway_cancel_final_race",
        # ISSUE 18: cross-replica carry migration
        "stepbatch_kill_during_carry_export", "stepbatch_migrate_vs_cancel",
        # ISSUE 19: fused cohort step dispatch
        "stepbatch_preempt_vs_pack_race",
        # ISSUE 20: AOT cache + elastic autoscale
        "autoscale_down_vs_carry_export",
    }


def test_cli_gate_subprocess(tmp_path):
    out = tmp_path / "conc.json"
    proc = subprocess.run(
        [sys.executable, "-m", "distrifuser_tpu.analysis.concurrency",
         "--schedules", "2", "--scenario", "submit_stop_race",
         "--json", str(out)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    import json

    report = json.loads(out.read_text())
    assert report["schedules_explored"] == 2
    assert report["new"] == 0 and report["failures"] == 0


# ---------------------------------------------------------------------------
# registry coverage: via= entries bridge the two passes


def test_via_entries_join_drift_coverage():
    covered = _registry_coverage()
    # a cross-object (via=) entry counts as covered for the drift check;
    # coverage is keyed by (module path, class) so a same-named class
    # elsewhere cannot inherit it
    key = ("distrifuser_tpu/serve/fleet.py", "_ReplicaSlot")
    assert "probe_inflight" in covered[key]
    assert ("distrifuser_tpu/serve/server.py",
            "_ReplicaSlot") not in covered
    # and via entries are marked as such in the registry
    fleet = GUARDED_REGISTRY["distrifuser_tpu/serve/fleet.py"]
    assert fleet["_ReplicaSlot"].via
    assert not fleet["FleetRouter"].via


# ---------------------------------------------------------------------------
# sync-containment checker


def _scan(src: str, relpath: str = "distrifuser_tpu/serve/fixture.py"):
    return sync_containment.scan_module(ast.parse(src), relpath)


def test_sync_containment_flags_raw_constructor():
    findings = _scan(
        "import threading\n\n"
        "def make():\n"
        "    return threading.Lock()\n")
    assert len(findings) == 1
    assert findings[0].identity == "make:threading.Lock:0"


def test_sync_containment_resolves_aliases():
    assert _scan("import threading as t\n\nx = t.Event()\n")
    assert _scan("from threading import Thread as T\n\n"
                 "def go(fn):\n    T(target=fn).start()\n")
    assert _scan("import queue\n\nq = queue.Queue()\n")


def test_sync_containment_blesses_the_sync_layer():
    src = "import threading\n\nx = threading.Lock()\n"
    assert _scan(src, "distrifuser_tpu/utils/sync.py") == []


def test_sync_containment_ignores_non_constructors():
    assert _scan("import threading\n\n"
                 "name = threading.current_thread().name\n") == []


def test_sync_containment_clean_on_real_tree():
    from distrifuser_tpu.analysis import CheckContext

    assert sync_containment.run(CheckContext(REPO)) == []


def test_harness_restores_instrumentation_exactly():
    """A harness run leaves the process as it found it: classes that
    merely INHERITED __setattr__ must not keep the instrumentation
    wrapper in their class dict after restore (a stuck wrapper taxes
    every attribute write for the rest of the process and
    double-records on the next run)."""
    from distrifuser_tpu.serve.testing import (
        FakeExecutorFactory,
        LedgerFakeExecutorFactory,
    )

    for cls in (FakeExecutorFactory, LedgerFakeExecutorFactory):
        assert "__setattr__" not in vars(cls)
    run_schedule(SCENARIOS["failover_exactly_once"], 0, name="restore")
    for cls in (FakeExecutorFactory, LedgerFakeExecutorFactory):
        assert "__setattr__" not in vars(cls), (
            f"{cls.__name__} kept the instrumentation wrapper")


# ---------------------------------------------------------------------------
# production passthrough: no runtime installed => stdlib objects


def test_sync_passthrough_returns_stdlib_objects():
    import queue
    import threading

    assert sync.active_runtime() is None
    assert isinstance(sync.Lock(), type(threading.Lock()))
    assert isinstance(sync.RLock(), type(threading.RLock()))
    assert isinstance(sync.Event(), threading.Event)
    assert isinstance(sync.Condition(), threading.Condition)
    assert isinstance(sync.Semaphore(2), threading.Semaphore)
    assert isinstance(sync.Queue(), queue.Queue)
    t = sync.Thread(target=lambda: None, name="x", daemon=True)
    assert isinstance(t, threading.Thread) and t.daemon


def test_nested_runtime_install_rejected():
    class _Fake:
        pass

    sync.install_runtime(_Fake())
    try:
        with pytest.raises(RuntimeError):
            sync.install_runtime(_Fake())
    finally:
        sync.uninstall_runtime()

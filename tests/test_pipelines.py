"""Pipeline-level tests with tiny random-weight models on the fake mesh."""

import jax
import numpy as np
import pytest

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models.clip import init_clip_params, tiny_clip_config
from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.models.vae import init_vae_params, tiny_vae_config
from distrifuser_tpu.pipelines import (
    DistriSDPipeline,
    DistriSDXLPipeline,
    SimpleTokenizer,
)


def build_sdxl_pipeline(devices, n_dev, **cfg_kw):
    cfg_kw.setdefault("height", 128)
    cfg_kw.setdefault("width", 128)
    cfg_kw.setdefault("warmup_steps", 1)
    dcfg = DistriConfig(devices=devices[:n_dev], **cfg_kw)
    # SDXL-shaped tiny stack: the two encoders' hidden widths concat to the
    # UNet cross_attention_dim (16+16=32); pooled embeds use encoder 2's
    # projection, which must match ucfg's text_embeds width (32)
    from distrifuser_tpu.models.clip import CLIPTextConfig

    tc1 = tiny_clip_config(hidden=16)
    tc2 = CLIPTextConfig(
        vocab_size=1000, hidden_size=16, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=32, projection_dim=32,
    )
    ucfg = tiny_config(cross_attention_dim=32, sdxl=True)
    vcfg = tiny_vae_config()
    pipe = DistriSDXLPipeline.from_params(
        dcfg,
        ucfg,
        init_unet_params(jax.random.PRNGKey(0), ucfg),
        vcfg,
        init_vae_params(jax.random.PRNGKey(1), vcfg),
        [tc1, tc2],
        [
            init_clip_params(jax.random.PRNGKey(2), tc1),
            init_clip_params(jax.random.PRNGKey(3), tc2),
        ],
    )
    return pipe, dcfg


def build_sd_pipeline(devices, n_dev, **cfg_kw):
    cfg_kw.setdefault("height", 128)
    cfg_kw.setdefault("width", 128)
    cfg_kw.setdefault("warmup_steps", 1)
    dcfg = DistriConfig(devices=devices[:n_dev], **cfg_kw)
    tc = tiny_clip_config(hidden=32)
    ucfg = tiny_config(cross_attention_dim=32, sdxl=False)
    vcfg = tiny_vae_config()
    pipe = DistriSDPipeline.from_params(
        dcfg, ucfg,
        init_unet_params(jax.random.PRNGKey(0), ucfg),
        vcfg, init_vae_params(jax.random.PRNGKey(1), vcfg),
        [tc], [init_clip_params(jax.random.PRNGKey(2), tc)],
    )
    return pipe, dcfg


def test_sdxl_pipeline_generates_pil(devices8):
    pipe, _ = build_sdxl_pipeline(devices8, 8)
    out = pipe("a photo of an astronaut riding a horse", num_inference_steps=3, seed=7)
    img = out.images[0]
    # tiny VAE has 2 blocks -> one 2x upsample: 16x16 latent -> 32x32 pixels
    assert img.size == (32, 32)
    arr = np.asarray(img)
    assert arr.dtype == np.uint8 and arr.shape == (32, 32, 3)


def test_sdxl_deterministic_per_seed(devices8):
    pipe, _ = build_sdxl_pipeline(devices8, 4)
    a = pipe("a corgi", num_inference_steps=2, seed=1, output_type="np").images[0]
    b = pipe("a corgi", num_inference_steps=2, seed=1, output_type="np").images[0]
    c = pipe("a corgi", num_inference_steps=2, seed=2, output_type="np").images[0]
    np.testing.assert_array_equal(a, b)
    assert np.abs(a - c).max() > 0


def test_sdxl_multi_device_matches_single(devices8):
    """Pipeline-level golden test (the reference's §4 protocol as a unit test)."""
    pipe1, _ = build_sdxl_pipeline(devices8, 1)
    pipe8, _ = build_sdxl_pipeline(devices8, 8, mode="full_sync")
    kw = dict(num_inference_steps=3, seed=11, output_type="np")
    img1 = pipe1("a lighthouse at dusk", **kw).images[0]
    img8 = pipe8("a lighthouse at dusk", **kw).images[0]
    # uint8-scale agreement: PSNR > 30 dB (the reference's quality bar)
    mse = float(np.mean((img1 - img8) ** 2))
    psnr = 10 * np.log10(1.0 / max(mse, 1e-12))
    assert psnr > 30, f"PSNR {psnr:.1f} dB"


def test_sd_pipeline_latent_output(devices8):
    pipe, dcfg = build_sd_pipeline(devices8, 4)
    out = pipe("a cat", num_inference_steps=2, seed=3, output_type="latent")
    assert len(out.images) == 1  # one entry per image, like 'np'/'pil'
    lat = out.images[0]
    assert lat.shape == (dcfg.latent_height, dcfg.latent_width, 4)
    assert np.isfinite(lat).all()


def test_pipeline_rejects_runtime_size(devices8):
    pipe, _ = build_sd_pipeline(devices8, 2)
    with pytest.raises(ValueError, match="fixed in DistriConfig"):
        pipe("a cat", height=512)


def test_guidance_forced_off_without_cfg(devices8):
    pipe, _ = build_sd_pipeline(devices8, 4, do_classifier_free_guidance=False)
    out = pipe("a cat", num_inference_steps=2, guidance_scale=9.0, output_type="latent")
    assert np.isfinite(out.images[0]).all()


def test_batch_of_prompts(devices8):
    pipe, dcfg = build_sd_pipeline(devices8, 4, batch_size=2)
    out = pipe(["a cat", "a dog"], num_inference_steps=2, output_type="latent")
    assert len(out.images) == 2
    lat = np.stack(out.images)
    assert lat.shape == (2, dcfg.latent_height, dcfg.latent_width, 4)
    assert np.isfinite(lat).all()
    # fewer prompts than batch_size: padded internally, one image back
    one = pipe("just one", num_inference_steps=2, output_type="latent")
    assert len(one.images) == 1


def test_prompt_chunking_matches_manual_chunks(devices8):
    """3 prompts through a batch_size=2 pipeline == the two manual chunk
    calls with the same per-image initial noise (VERDICT r3 task 8: arbitrary
    prompt counts chunk instead of asserting)."""
    pipe, _ = build_sd_pipeline(devices8, 2, batch_size=2)
    lats = np.asarray(jax.random.normal(jax.random.PRNGKey(9), (3, 16, 16, 4)))
    kw = dict(num_inference_steps=2, output_type="latent")
    all3 = pipe(["a cat", "a dog", "a bird"], latents=lats, **kw).images
    assert len(all3) == 3
    first2 = pipe(["a cat", "a dog"], latents=lats[:2], **kw).images
    # the tail chunk pads internally; hand it the padded latents explicitly
    last1 = pipe(["a bird", "a bird"], latents=np.concatenate(
        [lats[2:], lats[2:]]), **kw).images
    np.testing.assert_array_equal(np.stack(all3[:2]), np.stack(first2))
    np.testing.assert_array_equal(all3[2], last1[0])


def test_chunked_decode_and_empty_prompts(devices8):
    """The decode path handles totals that are not a batch_size multiple
    (chunked VAE decode), and an empty prompt list fails with a clear
    message."""
    pipe, _ = build_sd_pipeline(devices8, 2, batch_size=2)
    out = pipe(["a cat", "a dog", "a bird"], num_inference_steps=2,
               output_type="np")
    assert len(out.images) == 3
    assert all(np.isfinite(im).all() for im in out.images)
    with pytest.raises(AssertionError, match="at least one prompt"):
        pipe([], num_inference_steps=2)


def test_num_images_per_prompt(devices8):
    """num_images_per_prompt expands prompt-major (diffusers order): the
    expanded call equals an explicit repeated-prompt call on the same
    latents."""
    pipe, _ = build_sd_pipeline(devices8, 2, batch_size=2)
    lats = np.asarray(jax.random.normal(jax.random.PRNGKey(4), (4, 16, 16, 4)))
    kw = dict(num_inference_steps=2, output_type="latent")
    expanded = pipe(["a cat", "a dog"], num_images_per_prompt=2,
                    latents=lats, **kw).images
    explicit = pipe(["a cat", "a cat", "a dog", "a dog"],
                    latents=lats, **kw).images
    assert len(expanded) == 4
    np.testing.assert_array_equal(np.stack(expanded), np.stack(explicit))
    # different noise per image of the same prompt
    assert np.abs(expanded[0] - expanded[1]).max() > 0


def test_sdxl_batch_prompts(devices8):
    pipe, dcfg = build_sdxl_pipeline(devices8, 4, batch_size=2)
    out = pipe(
        ["a red fox", "a blue bird"],
        negative_prompt=["blurry", "low quality"],
        num_inference_steps=2,
        output_type="latent",
    )
    assert len(out.images) == 2
    lat = np.stack(out.images)
    assert lat.shape == (2, dcfg.latent_height, dcfg.latent_width, 4)
    assert np.isfinite(lat).all()


def test_img2img_wiring_matches_manual_latents(devices8):
    """strength=1.0 img2img == text2img fed the manually noised encode of
    the same image (pins the encode -> add_noise -> generate wiring), and a
    partial strength runs fewer steps from a closer start."""
    import jax.numpy as jnp

    from distrifuser_tpu.models import vae as vae_mod

    pipe, dcfg = build_sd_pipeline(devices8, 2)
    rng = np.random.RandomState(7)
    im = rng.rand(32, 32, 3).astype(np.float32)  # [0,1], decoder-sized
    kw = dict(num_inference_steps=4, output_type="latent", seed=11)

    out_i2i = pipe("a cabin", image=im, strength=1.0, **kw).images[0]

    init = pipe._encode_image(
        pipe.vae_params, jnp.asarray((im * 2 - 1)[None])
    ) * pipe.vae_config.scaling_factor
    pipe.scheduler.set_timesteps(4)
    noise = jax.random.normal(jax.random.PRNGKey(11), init.shape, jnp.float32)
    manual = pipe.scheduler.add_noise(init, noise, 0)
    out_manual = pipe("a cabin", latents=np.asarray(manual), **kw).images[0]
    np.testing.assert_array_equal(out_i2i, out_manual)

    # partial strength: still finite, and output differs (fewer steps, start
    # closer to the init image)
    out_half = pipe("a cabin", image=im, strength=0.5, **kw).images[0]
    assert np.isfinite(out_half).all()
    assert np.abs(out_half - out_i2i).max() > 0
    with pytest.raises(AssertionError, match="not both"):
        pipe("a cabin", image=im, latents=np.asarray(manual), **kw)


def test_img2img_low_strength_stays_closer_to_init(devices8):
    """Lower strength must reconstruct the init latent more closely — the
    user-visible img2img contract."""
    import jax.numpy as jnp

    from distrifuser_tpu.models import vae as vae_mod

    pipe, _ = build_sd_pipeline(devices8, 1)
    rng = np.random.RandomState(8)
    im = rng.rand(32, 32, 3).astype(np.float32)
    init = np.asarray(vae_mod.encode(
        pipe.vae_params, pipe.vae_config, jnp.asarray((im * 2 - 1)[None])
    ) * pipe.vae_config.scaling_factor)
    kw = dict(num_inference_steps=8, output_type="latent", seed=3)
    d = {}
    for s in (0.25, 1.0):
        out = pipe("a cabin", image=im, strength=s, **kw).images[0]
        d[s] = float(np.abs(out - init[0]).mean())
    assert d[0.25] < d[1.0], d


def test_sdxl_micro_conditioning_kwargs(devices8):
    """original_size / crops / target_size flow into the SDXL time_ids
    (diffusers kwargs the reference forwards): explicit defaults equal the
    implicit ones bitwise; a different original_size changes the output."""
    pipe, dcfg = build_sdxl_pipeline(devices8, 2)
    kw = dict(num_inference_steps=2, output_type="latent", seed=5)
    base = pipe("a fox", **kw).images[0]
    explicit = pipe("a fox", original_size=(dcfg.height, dcfg.width),
                    crops_coords_top_left=(0, 0),
                    target_size=(dcfg.height, dcfg.width), **kw).images[0]
    np.testing.assert_array_equal(base, explicit)
    shifted = pipe("a fox", original_size=(4 * dcfg.height, 4 * dcfg.width),
                   crops_coords_top_left=(64, 64), **kw).images[0]
    assert np.abs(shifted - base).max() > 0
    # 6-id base layout (diffusers 0.24.0 gating): a LONE negative size is
    # ignored — the uncond branch reuses the positive add_time_ids unless
    # BOTH negative_original_size AND negative_target_size are passed
    lone = pipe("a fox", negative_original_size=(4 * dcfg.height,
                                                 4 * dcfg.width),
                **kw).images[0]
    np.testing.assert_array_equal(base, lone)
    # with both given, the negative set reaches ONLY the uncond branch:
    # values equal to the positive defaults are a bitwise no-op, an
    # asymmetric negative size changes the output
    sym = pipe("a fox", negative_original_size=(dcfg.height, dcfg.width),
               negative_target_size=(dcfg.height, dcfg.width),
               **kw).images[0]
    np.testing.assert_array_equal(base, sym)
    asym = pipe("a fox", negative_original_size=(4 * dcfg.height,
                                                 4 * dcfg.width),
                negative_target_size=(dcfg.height, dcfg.width),
                **kw).images[0]
    assert np.abs(asym - base).max() > 0
    # custom positive crops are REUSED by the uncond branch when the
    # negative set is inactive; activating it resets uncond crops to (0, 0)
    # unless negative_crops_coords_top_left overrides them
    crop = pipe("a fox", crops_coords_top_left=(32, 32), **kw).images[0]
    crop_reused = pipe("a fox", crops_coords_top_left=(32, 32),
                       negative_original_size=(dcfg.height, dcfg.width),
                       negative_target_size=(dcfg.height, dcfg.width),
                       negative_crops_coords_top_left=(32, 32),
                       **kw).images[0]
    np.testing.assert_array_equal(crop, crop_reused)
    crop_zeroed = pipe("a fox", crops_coords_top_left=(32, 32),
                       negative_original_size=(dcfg.height, dcfg.width),
                       negative_target_size=(dcfg.height, dcfg.width),
                       **kw).images[0]
    assert np.abs(crop_zeroed - crop).max() > 0


def test_refiner_layout_aesthetic_score(devices8):
    """5-id refiner-style UNet: aesthetic_score conditions the positive
    branch, negative_aesthetic_score (diffusers default 2.5) the uncond
    branch — so the branches differ by default and equalizing the scores
    changes the output."""
    import dataclasses

    from distrifuser_tpu.models.clip import CLIPTextConfig, init_clip_params
    from distrifuser_tpu.models.unet import init_unet_params, tiny_config
    from distrifuser_tpu.models.vae import init_vae_params, tiny_vae_config
    from distrifuser_tpu.pipelines import DistriSDXLPipeline

    from distrifuser_tpu import DistriConfig
    from distrifuser_tpu.models.clip import tiny_clip_config

    dcfg = DistriConfig(devices=devices8[:2], height=128, width=128,
                        warmup_steps=1)
    tc1 = tiny_clip_config(hidden=16)
    tc2 = CLIPTextConfig(vocab_size=1000, hidden_size=16, num_hidden_layers=2,
                         num_attention_heads=4, intermediate_size=32,
                         projection_dim=32)
    base_ucfg = tiny_config(cross_attention_dim=32, sdxl=True)
    # pooled(32) + 5 * addition_time_embed_dim(8) = 72: the refiner layout
    ucfg = dataclasses.replace(base_ucfg,
                               projection_class_embeddings_input_dim=72)
    pipe = DistriSDXLPipeline.from_params(
        dcfg, ucfg, init_unet_params(jax.random.PRNGKey(0), ucfg),
        tiny_vae_config(),
        init_vae_params(jax.random.PRNGKey(1), tiny_vae_config()),
        [tc1, tc2],
        [init_clip_params(jax.random.PRNGKey(2), tc1),
         init_clip_params(jax.random.PRNGKey(3), tc2)],
    )
    kw = dict(num_inference_steps=2, output_type="latent", seed=5)
    default = pipe("a fox", **kw).images[0]  # scores 6.0 vs 2.5
    equalized = pipe("a fox", negative_aesthetic_score=6.0, **kw).images[0]
    assert np.abs(default - equalized).max() > 0
    repeat = pipe("a fox", **kw).images[0]
    np.testing.assert_array_equal(default, repeat)


def test_denoising_split_equals_full_run(devices8):
    """Base+refiner split protocol: a run stopped at denoising_end plus a
    second run resumed at the same denoising_start must equal the
    uninterrupted run (single device: one-phase loop, so the handoff cannot
    change warmup semantics)."""
    pipe, dcfg = build_sd_pipeline(devices8, 1)
    noise = np.asarray(jax.random.normal(jax.random.PRNGKey(2), (1, 16, 16, 4)))
    kw = dict(num_inference_steps=6, output_type="latent")
    full = pipe("a canyon", latents=noise, **kw).images[0]
    mid = pipe("a canyon", latents=noise, denoising_end=0.5, **kw).images[0]
    assert np.abs(mid - full).max() > 0  # actually stopped early
    resumed = pipe("a canyon", latents=mid[None], denoising_start=0.5,
                   **kw).images[0]
    # bitwise equality does not survive XLA compiling the three loop
    # programs separately (float re-association); 1e-4 on O(30) latents
    # is ~1e-5 relative
    np.testing.assert_allclose(resumed, full, atol=1e-4)
    with pytest.raises(AssertionError, match="mid-trajectory"):
        pipe("a canyon", denoising_start=0.5, **kw)


def test_simple_tokenizer_shapes():
    tok = SimpleTokenizer()
    ids = tok(["hello world", ""])
    assert ids.shape == (2, 77)
    assert ids[0, 0] == tok.bos
    assert (ids[1] == tok.eos).sum() >= 76


def test_rectangular_image(devices8):
    pipe, dcfg = build_sd_pipeline(devices8, 4, height=192, width=128)
    out = pipe("a waterfall", num_inference_steps=2, output_type="latent")
    assert len(out.images) == 1
    lat = out.images[0]
    assert lat.shape == (24, 16, 4)
    assert np.isfinite(lat).all()


def test_caller_supplied_latents(devices8):
    pipe, dcfg = build_sd_pipeline(devices8, 2)
    lat0 = np.asarray(
        jax.random.normal(jax.random.PRNGKey(0), (1, 16, 16, 4))
    )
    a = pipe("a pier", num_inference_steps=2, latents=lat0, output_type="np").images[0]
    b = pipe("a pier", num_inference_steps=2, latents=lat0, output_type="np").images[0]
    np.testing.assert_array_equal(a, b)
    with pytest.raises(AssertionError):
        pipe("a pier", num_inference_steps=2, latents=lat0[:, :8])


def test_weightless_tokenizer_flag_on_output(devices8):
    """Hash-tokenizer runs carry the warning ON the artifact (VERDICT r4
    weak #5): the PipelineOutput says it must not be quality-judged; a
    real-tokenizer pipeline emits a clean output."""
    pipe, _ = build_sdxl_pipeline(devices8, 1)
    out = pipe("a fox", num_inference_steps=1, output_type="latent", seed=0)
    assert out.weightless_tokenizer
    assert "SimpleTokenizer" in out.warning

    class _FakeRealTok:
        model_max_length = 77

        def __call__(self, texts, max_length=77, **kw):
            return {"input_ids": np.zeros((len(texts), max_length), np.int64)}

    pipe.tokenizers = [_FakeRealTok(), _FakeRealTok()]
    out2 = pipe("a fox", num_inference_steps=1, output_type="latent", seed=0)
    assert not out2.weightless_tokenizer and out2.warning is None

"""CLI-level metrics fixture: all three metrics end-to-end through
scripts/compute_metrics.py.

VERDICT r2 #5: the LPIPS/FID *math* was tested weight-free, but the weight
LOADING paths (torch.load state dict, torch.jit.load TorchScript) had never
executed.  This fixture checks in that proof: a synthetic AlexNet+LPIPS
state dict and a random-weight TorchScript extractor are written to disk
exactly in the offline artifact formats the CLI documents, two image
directories are generated, and the CLI must print a parseable number for
PSNR, LPIPS, and FID — so the only missing ingredient for published-table
comparability is ever the real weight files (reference computes all three,
/root/reference/scripts/compute_metrics.py:53-79).
"""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

torch = pytest.importorskip("torch")
from PIL import Image

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "scripts", "compute_metrics.py")


def _write_image_dirs(tmp_path, n=6, size=64, seed=0):
    r = np.random.RandomState(seed)
    roots = []
    for j in range(2):
        root = tmp_path / f"imgs{j}"
        root.mkdir()
        roots.append(str(root))
    for i in range(n):
        base = r.randint(0, 255, (size, size, 3)).astype(np.uint8)
        noisy = np.clip(
            base.astype(np.int16) + r.randint(-20, 20, base.shape), 0, 255
        ).astype(np.uint8)
        Image.fromarray(base).save(os.path.join(roots[0], f"{i:04d}.png"))
        Image.fromarray(noisy).save(os.path.join(roots[1], f"{i:04d}.png"))
    return roots


def _write_lpips_fixture(path, seed=0):
    """Synthetic weights in the documented merged AlexNet+LPIPS layout."""
    from distrifuser_tpu.utils import metrics as m

    r = np.random.RandomState(seed)
    state = {}
    for i, (co, ci, k, _, _, _) in zip(m._ALEX_IDX, m._ALEX_CONVS):
        state[f"features.{i}.weight"] = torch.tensor(
            r.randn(co, ci, k, k).astype(np.float32) * 0.05
        )
        state[f"features.{i}.bias"] = torch.zeros(co)
    for i, (co, _, _, _, _, _) in enumerate(m._ALEX_CONVS):
        state[f"lin{i}.model.1.weight"] = torch.tensor(
            np.abs(r.randn(1, co, 1, 1).astype(np.float32))
        )
    torch.save(state, path)


class _TinyExtractor(torch.nn.Module):
    """Random-weight stand-in with the pt_inception contract:
    [N,3,299,299] float in [0,1] -> [N,D] features."""

    def __init__(self, dim=16):
        super().__init__()
        self.conv = torch.nn.Conv2d(3, dim, kernel_size=7, stride=4)
        self.pool = torch.nn.AdaptiveAvgPool2d(1)

    def forward(self, x):
        return self.pool(torch.relu(self.conv(x))).flatten(1)


def _write_fid_fixture(path, seed=0):
    torch.manual_seed(seed)
    mod = torch.jit.script(_TinyExtractor())
    torch.jit.save(mod, path)


def test_compute_metrics_cli_all_three(tmp_path):
    root0, root1 = _write_image_dirs(tmp_path)
    lpips_path = str(tmp_path / "lpips_fixture.pth")
    fid_path = str(tmp_path / "fid_fixture.pt")
    _write_lpips_fixture(lpips_path)
    _write_fid_fixture(fid_path)

    out = subprocess.run(
        [sys.executable, CLI,
         "--input_root0", root0, "--input_root1", root1,
         "--lpips_weights", lpips_path, "--fid_weights", fid_path,
         "--batch_size", "4"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO},
    )
    assert out.returncode == 0, out.stderr
    psnr_m = re.search(r"PSNR: ([\d.]+) dB", out.stdout)
    lpips_m = re.search(r"LPIPS: ([\d.]+)", out.stdout)
    fid_m = re.search(r"FID: ([\d.]+)", out.stdout)
    assert psnr_m and lpips_m and fid_m, out.stdout
    # same-vs-noisy pairs: PSNR finite and plausible, LPIPS/FID >= 0 finite
    assert 5.0 < float(psnr_m.group(1)) < 60.0
    assert np.isfinite(float(lpips_m.group(1)))
    assert np.isfinite(float(fid_m.group(1)))
    assert "unavailable" not in out.stdout


def test_compute_metrics_cli_identical_dirs_degenerate(tmp_path):
    """Identical dirs: FID ~ 0 and LPIPS ~ 0 pin the metric conventions."""
    root0, _ = _write_image_dirs(tmp_path)
    lpips_path = str(tmp_path / "lpips_fixture.pth")
    fid_path = str(tmp_path / "fid_fixture.pt")
    _write_lpips_fixture(lpips_path)
    _write_fid_fixture(fid_path)

    out = subprocess.run(
        [sys.executable, CLI,
         "--input_root0", root0, "--input_root1", root0,
         "--lpips_weights", lpips_path, "--fid_weights", fid_path],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "PYTHONPATH": REPO},
    )
    assert out.returncode == 0, out.stderr
    lpips_m = re.search(r"LPIPS: ([\d.]+)", out.stdout)
    fid_m = re.search(r"FID: (-?[\d.e+-]+)", out.stdout)
    assert float(lpips_m.group(1)) < 1e-6
    assert abs(float(fid_m.group(1))) < 1e-3

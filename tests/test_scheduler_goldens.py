"""Scheduler golden tests: exact analytic trajectories + independent references.

Two layers of defense (diffusers is not installed on this box, so diffusers
==0.24 cannot be imported; these replace bit-parity with math):

1. **Point-mass exactness** (closed form).  For a point-mass data
   distribution at ``x0``, the exact epsilon is recoverable at every noise
   level, and each sampler's update must map ``alpha_t*x0 + sigma_t*n``
   EXACTLY to ``alpha_prev*x0 + sigma_prev*n`` with the *same* ``n`` — for any
   step size, any schedule.  This pins every coefficient and table index of
   DDIM / Euler / DPM++ (and the v-prediction conversion) analytically; an
   off-by-one in the alpha/sigma tables or a sign error in the update cannot
   pass.  (Derivation: DDIM eq.(12) of arXiv:2010.02502 with eta=0;
   DPM-Solver++ first-order update of arXiv:2211.01095 — exact x0 makes the
   2M correction a no-op.)

2. **Independent 2M reference.**  The multistep correction is invisible to
   (1), so a from-the-paper numpy implementation of DPM-Solver++(2M) —
   written in diffusers' list-carry style, deliberately NOT sharing the scan
   carry-state code under test — is driven by a nonlinear fake model and must
   match the jnp implementation step for step.  Tail convention: final sigma
   = 0, last step first-order (diffusers lower_order_final=True,
   final_sigmas_type="zero").

Table goldens (leading spacing, steps_offset=1) are hand-computed:
1000 train steps / 50 inference steps -> timesteps 981, 961, ..., 21, 1.
"""

import numpy as np
import pytest

from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.schedulers.scheduling import (
    _leading_timesteps,
    _make_alphas_cumprod,
)

SHAPE = (2, 4, 4, 3)


def _tables(steps):
    ac = _make_alphas_cumprod(1000, 0.00085, 0.012, "scaled_linear")
    ts = _leading_timesteps(1000, steps, 1)
    return ac, ts


def _rand(seed):
    r = np.random.RandomState(seed)
    return r.randn(*SHAPE).astype(np.float64)


# ---------------------------------------------------------------------------
# table goldens
# ---------------------------------------------------------------------------

def test_leading_timesteps_golden():
    ts = _leading_timesteps(1000, 50, 1)
    assert ts[0] == 981 and ts[1] == 961 and ts[-1] == 1
    assert len(ts) == 50 and np.all(np.diff(ts) == -20)
    # 25-step case: ratio 40
    ts25 = _leading_timesteps(1000, 25, 1)
    assert ts25[0] == 961 and ts25[-1] == 1 and np.all(np.diff(ts25) == -40)


def test_scaled_linear_betas_golden():
    ac = _make_alphas_cumprod(1000, 0.00085, 0.012, "scaled_linear")
    # beta_0 = 0.00085 exactly; beta_999 = 0.012 exactly
    assert ac[0] == pytest.approx(1 - 0.00085, rel=1e-12)
    assert len(ac) == 1000 and ac[-1] < 5e-3  # SD's terminal alpha_bar ~ 0.0047
    assert np.all(np.diff(ac) < 0)


# ---------------------------------------------------------------------------
# 1. point-mass exactness (closed-form trajectories)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("steps", [7, 50])
@pytest.mark.parametrize("pred", ["epsilon", "v_prediction"])
def test_ddim_point_mass_exact(steps, pred):
    ac, ts = _tables(steps)
    a = np.sqrt(ac[ts])
    s = np.sqrt(1 - ac[ts])
    prev = ts - 1000 // steps
    ac_prev = np.where(prev >= 0, ac[np.clip(prev, 0, None)], ac[0])
    a_p, s_p = np.sqrt(ac_prev), np.sqrt(1 - ac_prev)

    x0, n = _rand(0), _rand(1)
    sched = get_scheduler("ddim", prediction_type=pred).set_timesteps(steps)
    state = sched.init_state(SHAPE)
    x = a[0] * x0 + s[0] * n
    for i in range(steps):
        if pred == "epsilon":
            out = n
        else:
            out = a[i] * n - s[i] * x0  # v-target of the point mass
        x, state = sched.step(x, out, i, state)
        expect = a_p[i] * x0 + s_p[i] * n
        np.testing.assert_allclose(np.asarray(x), expect, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("steps", [7, 50])
@pytest.mark.parametrize("pred", ["epsilon", "v_prediction"])
def test_euler_point_mass_exact(steps, pred):
    ac, ts = _tables(steps)
    sig = np.append(((1 - ac[ts]) / ac[ts]) ** 0.5, 0.0)

    x0, n = _rand(2), _rand(3)
    sched = get_scheduler("euler", prediction_type=pred).set_timesteps(steps)
    state = sched.init_state(SHAPE)
    x = x0 + sig[0] * n  # sigma-space parameterization
    for i in range(steps):
        # the model sees the descaled (VP) input; alpha_bar = 1/(sigma^2+1)
        av = 1.0 / np.sqrt(sig[i] ** 2 + 1.0)
        sv = sig[i] * av
        scaled = np.asarray(sched.scale_model_input(x, i))
        np.testing.assert_allclose(
            scaled, av * (x0 + sig[i] * n), rtol=2e-5, atol=1e-5
        )
        out = n if pred == "epsilon" else av * n - sv * x0
        x, state = sched.step(x, out, i, state)
        np.testing.assert_allclose(
            np.asarray(x), x0 + sig[i + 1] * n, rtol=2e-4, atol=2e-5
        )
    np.testing.assert_allclose(np.asarray(x), x0, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("steps", [7, 50])
@pytest.mark.parametrize("pred", ["epsilon", "v_prediction"])
def test_dpm_point_mass_exact(steps, pred):
    ac, ts = _tables(steps)
    a = np.append(np.sqrt(ac[ts]), 1.0)
    s = np.append(np.sqrt(1 - ac[ts]), 0.0)

    x0, n = _rand(4), _rand(5)
    sched = get_scheduler("dpm-solver", prediction_type=pred).set_timesteps(steps)
    state = sched.init_state(SHAPE)
    x = a[0] * x0 + s[0] * n
    for i in range(steps):
        out = n if pred == "epsilon" else a[i] * n - s[i] * x0
        x, state = sched.step(x, out, i, state)
        expect = a[i + 1] * x0 + s[i + 1] * n
        np.testing.assert_allclose(np.asarray(x), expect, rtol=3e-4, atol=3e-5)
    np.testing.assert_allclose(np.asarray(x), x0, rtol=3e-4, atol=3e-5)


# ---------------------------------------------------------------------------
# 2. independent references, nonlinear fake model (exercises 2M correction)
# ---------------------------------------------------------------------------

def _fake_eps(x, i):
    """Deterministic, nonlinear, step-dependent stand-in model."""
    return np.tanh(0.7 * np.asarray(x, np.float64)) + 0.05 * np.cos(float(i))


def _ddim_reference(x, steps):
    ac, ts = _tables(steps)
    ratio = 1000 // steps
    traj = []
    for i, t in enumerate(ts):
        eps = _fake_eps(x, i)
        a_t = ac[t]
        a_p = ac[t - ratio] if t - ratio >= 0 else ac[0]
        pred_x0 = (x - np.sqrt(1 - a_t) * eps) / np.sqrt(a_t)
        x = np.sqrt(a_p) * pred_x0 + np.sqrt(1 - a_p) * eps
        traj.append(x)
    return traj


def _euler_reference(x, steps):
    ac, ts = _tables(steps)
    sig = np.append(((1 - ac[ts]) / ac[ts]) ** 0.5, 0.0)
    traj = []
    for i in range(steps):
        eps = _fake_eps(x / np.sqrt(sig[i] ** 2 + 1.0), i)
        x = x + (sig[i + 1] - sig[i]) * eps  # d/dsigma of x = x0 + sigma*eps
        traj.append(x)
    return traj


def _dpm_2m_reference(x, steps):
    """DPM-Solver++(2M), list-carry style (arXiv:2211.01095 eq. (4.3)/(4.4);
    diffusers multistep_dpm_solver_second_order_update convention for r)."""
    ac, ts = _tables(steps)
    alpha = np.append(np.sqrt(ac[ts]), 1.0)
    sigma = np.append(np.sqrt(1 - ac[ts]), 0.0)
    with np.errstate(divide="ignore"):
        lam = np.log(alpha) - np.log(sigma)  # +inf at the appended tail
    x0_hist = []
    traj = []
    for i in range(steps):
        eps = _fake_eps(x, i)
        x0 = (x - sigma[i] * eps) / alpha[i]
        last = i == steps - 1
        if i == 0 or last:
            d = x0  # no history / lower_order_final
        else:
            h = lam[i + 1] - lam[i]
            h_prev = lam[i] - lam[i - 1]
            r = h_prev / h
            d = (1 + 1 / (2 * r)) * x0 - (1 / (2 * r)) * x0_hist[-1]
        if last:
            x = x0  # sigma_next = 0, expm1(-inf) = -1 -> alpha_next * D
        else:
            h = lam[i + 1] - lam[i]
            x = (sigma[i + 1] / sigma[i]) * x - alpha[i + 1] * np.expm1(-h) * d
        x0_hist.append(x0)
        traj.append(x)
    return traj


@pytest.mark.parametrize(
    "name,ref",
    [("ddim", _ddim_reference), ("euler", _euler_reference),
     ("dpm-solver", _dpm_2m_reference)],
)
@pytest.mark.parametrize("steps", [4, 13, 50])
def test_matches_independent_reference(name, ref, steps):
    sched = get_scheduler(name).set_timesteps(steps)
    state = sched.init_state(SHAPE)
    x_init = _rand(6) * float(sched.init_noise_sigma)
    expected = ref(x_init.copy(), steps)

    x = x_init.copy()
    for i in range(steps):
        model_in = np.asarray(sched.scale_model_input(x, i), np.float64)
        out = _fake_eps(model_in, i)
        x, state = sched.step(x, out, i, state)
        np.testing.assert_allclose(
            np.asarray(x), expected[i], rtol=5e-4, atol=5e-5,
            err_msg=f"{name} step {i}/{steps}",
        )

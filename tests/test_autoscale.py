"""Elastic fleet autoscaling (distrifuser_tpu/serve/autoscale.py): the
pressure math, dormant-start integration (only ``min_replicas`` warm at
``FleetRouter.start``), scale-up under sustained queue pressure,
drain-based scale-down that salvages mid-denoise work through carry
migration (zero re-executed steps), min/max bounds, hysteresis
(sustain windows + cooldown) on an injected clock, and the
fixed-fleet default staying untouched."""

import time

import pytest

from distrifuser_tpu.serve.autoscale import Autoscaler, fleet_pressure
from distrifuser_tpu.serve.fleet import FleetRouter, build_fleet
from distrifuser_tpu.serve.replica import (
    REPLICA_SERVING,
    REPLICA_STARTING,
    REPLICA_STOPPED,
    Replica,
)
from distrifuser_tpu.serve.testing import (
    ExecutionLedger,
    FakeExecutorFactory,
    StepLedgerFakeExecutorFactory,
)
from distrifuser_tpu.utils.config import (
    AutoscaleConfig,
    FleetConfig,
    ServeConfig,
    StepBatchConfig,
)
from distrifuser_tpu.utils.metrics import MetricsRegistry


def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, f"timed out waiting: {msg}"
        time.sleep(0.002)


def autoscale_cfg(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("pressure_high", 0.8)
    kw.setdefault("pressure_low", 0.1)
    kw.setdefault("up_sustain_s", 0.0)
    kw.setdefault("down_sustain_s", 0.0)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("drain_deadline_s", 5.0)
    return AutoscaleConfig(**kw)


def serve_cfg(**kw):
    kw.setdefault("warmup_buckets", ((64, 64, 2),))
    kw.setdefault("default_steps", 2)
    kw.setdefault("max_queue_depth", 64)
    kw.setdefault("default_ttl_s", 60.0)
    return ServeConfig(**kw)


def mk_fleet(n=3, *, factory=None, autoscale=None, serve=None, **fleet_kw):
    factory = factory or FakeExecutorFactory()
    fleet_kw.setdefault("tick_s", 0.0)
    fc = FleetConfig(autoscale=autoscale or autoscale_cfg(), **fleet_kw)
    return build_fleet(lambda name: factory, serve or serve_cfg(), fc,
                       replicas=[(f"r{i}", 1.0) for i in range(n)])


# --------------------------------------------------------------------------
# pressure math + defaults
# --------------------------------------------------------------------------


def test_fleet_pressure_math():
    assert fleet_pressure(0.0, 4.0) == 0.0
    assert fleet_pressure(2.0, 4.0) == 0.5
    assert fleet_pressure(8.0, 4.0) == 2.0
    assert fleet_pressure(0.0, 0.0) == 0.0
    assert fleet_pressure(1.0, 0.0) == float("inf")  # demand, no capacity


def test_autoscaler_absent_by_default():
    """The fixed-fleet default: no autoscaler, every replica starts."""
    router = mk_fleet(2, autoscale=AutoscaleConfig())  # enabled=False
    assert router.autoscaler is None
    with router:
        assert all(router.replica(n).state == REPLICA_SERVING
                   for n in router.replica_names())


# --------------------------------------------------------------------------
# dormant start: only min_replicas warm
# --------------------------------------------------------------------------


def test_start_warms_only_min_replicas():
    factory = FakeExecutorFactory()
    router = mk_fleet(3, factory=factory,
                      autoscale=autoscale_cfg(min_replicas=1))
    with router:
        assert router.replica("r0").state == REPLICA_SERVING
        assert router.replica("r1").state == REPLICA_STARTING
        assert router.replica("r2").state == REPLICA_STARTING
        assert router.autoscaler.active_count() == 1
        # dormant slots are routing-invisible but requests still serve
        out = router.submit("p", height=64, width=64,
                            num_inference_steps=2).result(timeout=30)
        assert out.replica == "r0"
    # only r0 ever built executors: the dormant slots cost no warmup
    assert all(router.replica(n).generation == (1 if n == "r0" else 0)
               for n in router.replica_names())


# --------------------------------------------------------------------------
# scale-up under sustained pressure
# --------------------------------------------------------------------------


def test_scale_up_on_sustained_queue_pressure(tmp_path):
    factory = FakeExecutorFactory(build_delay_s=0.05, step_time_s=0.05)
    serve = serve_cfg(max_batch_size=1)
    serve.aot_cache.dir = str(tmp_path)
    router = mk_fleet(3, factory=factory, serve=serve,
                      autoscale=autoscale_cfg(max_replicas=2))
    with router:
        a = router.autoscaler
        futs = [router.submit(f"p{i}", height=64, width=64,
                              num_inference_steps=2, seed=i)
                for i in range(8)]
        assert a.pressure() > a.config.pressure_high
        wait_for(lambda: (router.tick() or
                          router.replica("r1").state == REPLICA_SERVING),
                 msg="scale-up to r1")
        assert a.counters.snapshot()["scale_ups"] == 1
        assert a.active_count() == 2
        # the scaled-up replica warmed from the shared store: its build
        # skipped the delay (aot_warmed counts the instant builds)
        assert factory.aot_warmed >= 1
        for f in futs:
            assert f.result(timeout=30) is not None
    snap = router.metrics_snapshot()["fleet"]
    assert snap["autoscale"]["counters"]["scale_ups"] == 1


def test_scale_up_respects_max_replicas():
    factory = FakeExecutorFactory(step_time_s=0.05)
    serve = serve_cfg(max_batch_size=1)
    router = mk_fleet(3, factory=factory, serve=serve,
                      autoscale=autoscale_cfg(max_replicas=1))
    with router:
        a = router.autoscaler
        futs = [router.submit(f"p{i}", height=64, width=64,
                              num_inference_steps=2, seed=i)
                for i in range(6)]
        router.tick()
        assert a.counters.snapshot().get("up_blocked_max", 0) >= 1
        assert a.counters.snapshot().get("scale_ups", 0) == 0
        assert a.active_count() == 1
        for f in futs:
            f.result(timeout=30)


# --------------------------------------------------------------------------
# scale-down: drain rides carry migration, zero re-executed steps
# --------------------------------------------------------------------------


def _step_serve_cfg():
    return ServeConfig(
        max_queue_depth=32, max_batch_size=4, batch_window_s=0.001,
        buckets=((64, 64),), warmup_buckets=(), default_steps=4,
        default_ttl_s=60.0,
        step_batching=StepBatchConfig(enabled=True, slots=4))


def test_scale_down_salvages_in_flight_steps():
    """Idle pressure with a straggler mid-denoise: the victim drains at
    the deadline, its carry exports, and the request finishes on the
    survivor with every completed step executed exactly once."""
    registry = MetricsRegistry()
    ledger = ExecutionLedger()
    cfg = _step_serve_cfg()
    reps = [Replica(n, StepLedgerFakeExecutorFactory(
                ledger, replica=n, batch_size=4, step_time_s=0.02),
                cfg, registry=registry)
            for n in ("r0", "r1")]
    router = FleetRouter(reps, FleetConfig(tick_s=0.0), registry=registry)
    with router:
        # attached AFTER start so both replicas serve (the policy under
        # test is the drain decision, not the dormant-start path)
        a = Autoscaler(router, autoscale_cfg(
            min_replicas=1, pressure_low=0.5, drain_deadline_s=0.2))
        router.autoscaler = a
        steps = 60
        f0 = router.submit("keep", height=64, width=64, seed=1,
                           num_inference_steps=steps)
        f1 = router.submit("move", height=64, width=64, seed=2,
                           num_inference_steps=steps)
        wait_for(lambda: all(
            len(r.server.stepbatch.occupied()) == 1
            and all(s.steps_done >= 2
                    for s in r.server.stepbatch.occupied())
            for r in reps), msg="one request resident per replica")
        # 2 occupied / 8 slots = 0.25 <= pressure_low -> scale down;
        # equal pending, so the highest index (r1) is the victim
        assert a.pressure() <= 0.5
        assert a.tick() == "down"
        wait_for(lambda: router.replica("r1").state == REPLICA_STOPPED,
                 msg="victim released")
        outs = [f0.result(timeout=30), f1.result(timeout=30)]
    moved = outs[1]
    assert moved.replica == "r0" and moved.migrations == 1
    assert moved.steps_salvaged >= 2
    assert ledger.max_step_count() == 1  # ZERO re-executed steps
    snap = router.metrics_snapshot()["fleet"]["requests"]
    assert snap.get("fleet_steps_reexecuted", 0) == 0
    assert snap["steps_salvaged"] >= 2
    assert a.counters.snapshot()["scale_downs"] == 1


def test_scale_down_respects_min_replicas():
    router = mk_fleet(2, autoscale=autoscale_cfg(min_replicas=1,
                                                 down_sustain_s=0.0))
    with router:
        a = router.autoscaler
        # active == min: the idle fleet must never drain below the floor
        for _ in range(3):
            router.tick()
        assert a.active_count() == 1
        assert a.counters.snapshot().get("scale_downs", 0) == 0
        assert a.counters.snapshot().get("down_blocked_min", 0) >= 1


# --------------------------------------------------------------------------
# hysteresis on an injected clock: sustain windows + cooldown
# --------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_sustain_and_cooldown_injected_clock():
    clock = _Clock()
    factory = FakeExecutorFactory()
    fc = FleetConfig(tick_s=0.0, autoscale=autoscale_cfg(
        min_replicas=1, max_replicas=3,
        up_sustain_s=1.0, down_sustain_s=2.0, cooldown_s=5.0))
    router = build_fleet(lambda name: factory, serve_cfg(), fc,
                         replicas=[("r0", 1.0), ("r1", 1.0), ("r2", 1.0)],
                         clock=clock)
    with router:
        a = router.autoscaler
        demand = {"v": 10.0}
        a.pressure = lambda: demand["v"]  # policy-only determinism
        # sustained high pressure: no action until the window elapses
        assert a.tick(now=0.0) is None
        assert a.tick(now=0.5) is None
        assert a.tick(now=1.0) == "up"
        wait_for(lambda: not a.snapshot()["op_inflight"],
                 msg="scale-up op finished")
        assert router.replica("r1").state == REPLICA_SERVING
        # cooldown: pressure still high, but 5s must pass first
        assert a.tick(now=1.1) is None
        assert a.tick(now=5.9) is None
        assert a.tick(now=6.5) == "up"
        wait_for(lambda: not a.snapshot()["op_inflight"],
                 msg="second scale-up finished")
        assert a.active_count() == 3
        # a dip below low resets the HIGH mark; the low mark must also
        # sustain (2s) before a drain fires, cooldown permitting
        demand["v"] = 0.0
        assert a.tick(now=11.6) is None  # below_since = 11.6
        assert a.tick(now=12.6) is None  # 1.0s < down_sustain_s
        assert a.tick(now=13.7) == "down"
        wait_for(lambda: not a.snapshot()["op_inflight"],
                 msg="scale-down finished")
        assert a.active_count() == 2
        # a blip back above high wipes the low mark: no immediate drain
        demand["v"] = 10.0
        assert a.tick(now=18.8) is None  # above_since restarts
        demand["v"] = 0.0
        assert a.tick(now=18.9) is None  # below_since restarts at 18.9
        assert a.tick(now=19.9) is None  # not sustained yet
        cnt = a.counters.snapshot()
        assert cnt["scale_ups"] == 2 and cnt["scale_downs"] == 1

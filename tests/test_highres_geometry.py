"""Pod-scale geometry: the BASELINE.json north-star configs must trace.

Real 2048/3840 pixel counts with the tiny UNet on the fake 8-device mesh:
a full 2-step 2048x2048 generation executes, and the 3840x3840 8-way loop
(the reference's headline benchmark shape, README.md:30) traces and lowers
without shape errors — compile/execute at that size needs real chips, but
every sharding/divisibility decision is made at trace time.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.parallel.runner import DenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
import pytest


def test_2048_generation_executes(devices8):
    # tall rectangle: the full 2048-row sharding path at a CPU-friendly width
    ucfg = tiny_config()
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    cfg = DistriConfig(devices=devices8, height=2048, width=512, warmup_steps=0)
    runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    lat = jax.random.normal(
        jax.random.PRNGKey(1), (1, cfg.latent_height, cfg.latent_width, 4)
    )
    enc = jax.random.normal(jax.random.PRNGKey(2), (2, 1, 7, ucfg.cross_attention_dim))
    out = runner.generate(lat, enc, num_inference_steps=2)
    assert out.shape == lat.shape
    assert np.isfinite(np.asarray(out)).all()


def test_3840_8way_traces(devices8):
    ucfg = tiny_config()
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    cfg = DistriConfig(
        devices=devices8, height=3840, width=3840, warmup_steps=4,
        do_classifier_free_guidance=False,  # 8-way patch split
    )
    assert cfg.n_device_per_batch == 8
    runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    loop = runner._build(6)
    lat = jax.ShapeDtypeStruct((1, cfg.latent_height, cfg.latent_width, 4), jnp.float32)
    enc = jax.ShapeDtypeStruct((1, 1, 7, ucfg.cross_attention_dim), jnp.float32)
    gs = jax.ShapeDtypeStruct((), jnp.float32)
    lowered = loop.lower(runner.params, lat, enc, None, gs)
    assert lowered is not None


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

"""Native mmap safetensors reader vs the Python safetensors package."""

import numpy as np
import pytest

from distrifuser_tpu.native import available, load_safetensors_fast


@pytest.mark.skipif(not available(), reason="no native toolchain")
def test_fast_loader_matches_reference(tmp_path):
    from safetensors.numpy import load_file, save_file

    rng = np.random.RandomState(0)
    tensors = {
        "a.weight": rng.randn(64, 32).astype(np.float32),
        "b.bias": rng.randn(7).astype(np.float16),
        "c.table": rng.randint(-5, 5, size=(3, 4, 5)).astype(np.int32),
    }
    path = str(tmp_path / "t.safetensors")
    save_file(tensors, path)

    fast = load_safetensors_fast(path)
    ref = load_file(path)
    assert set(fast) == set(ref)
    for k in ref:
        assert fast[k].dtype == ref[k].dtype
        np.testing.assert_array_equal(fast[k], ref[k])


@pytest.mark.skipif(not available(), reason="no native toolchain")
def test_fast_loader_bf16(tmp_path):
    ml_dtypes = pytest.importorskip("ml_dtypes")
    from safetensors.numpy import save_file

    x = (np.random.RandomState(1).randn(16, 8).astype(np.float32)).astype(
        ml_dtypes.bfloat16
    )
    path = str(tmp_path / "bf16.safetensors")
    save_file({"w": x}, path)
    fast = load_safetensors_fast(path)
    assert fast["w"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(
        fast["w"].astype(np.float32), x.astype(np.float32)
    )


def test_missing_file_returns_none():
    assert load_safetensors_fast("/nonexistent/file.safetensors") in (None,)

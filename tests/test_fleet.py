"""Multi-replica fleet tests (serve/replica.py + serve/fleet.py) on the
deterministic weightless fakes: lifecycle state machine, weighted
routing math, failover without double execution, auto-drain + half-open
re-probe, heterogeneous capacity weights, drain semantics, deterministic
stop (including the stop-during-failover race), the ``"replica"`` fault
site, metrics namespacing, and 1-replica parity with the bare server."""

import threading
import time
import types

import pytest

from distrifuser_tpu.serve import (
    DeadlineExceededError,
    FaultPlan,
    FaultRule,
    FleetConfig,
    FleetRouter,
    InferenceServer,
    NoHealthyReplicaError,
    REPLICA_DRAINING,
    REPLICA_SERVING,
    REPLICA_STARTING,
    REPLICA_STOPPED,
    REPLICA_WARMING,
    Replica,
    ServeConfig,
    ServerClosedError,
    build_fleet,
    routing_weight,
)
from distrifuser_tpu.serve.faults import InjectedReplicaKilled
from distrifuser_tpu.serve.testing import (
    ExecutionLedger,
    FakeExecutorFactory,
    LedgerFakeExecutorFactory,
    fake_image,
)
from distrifuser_tpu.utils.config import ControllerConfig, ResilienceConfig
from distrifuser_tpu.utils.metrics import MetricsRegistry


class ManualClock:
    """Injectable clock driven by tests (same pattern as test_resilience)."""

    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def serve_config(**kw):
    kw.setdefault("max_queue_depth", 64)
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("batch_window_s", 0.0)
    kw.setdefault("buckets", ((512, 512),))
    kw.setdefault("default_steps", 4)
    kw.setdefault("warmup_buckets", ((512, 512, 4),))
    return ServeConfig(**kw)


def mk_fleet(replicas, fleet_config=None, *, config=None, clock=None,
             fault_plans=None, step_time_s=0.0, ledger=None):
    """Hand-built fleet (per-replica fault plans, shared registry)."""
    registry = MetricsRegistry()
    ledger = ledger if ledger is not None else ExecutionLedger()
    reps = []
    for name, weight in replicas:
        factory = LedgerFakeExecutorFactory(
            ledger, replica=name, batch_size=4, step_time_s=step_time_s)
        reps.append(Replica(
            name, factory, config or serve_config(),
            capacity_weight=weight,
            clock=clock or time.monotonic,
            fault_plan=(fault_plans or {}).get(name),
            registry=registry,
        ))
    fleet = FleetRouter(reps, fleet_config or FleetConfig(tick_s=0),
                        clock=clock or time.monotonic, registry=registry)
    return fleet, ledger


# --------------------------------------------------------------------------
# routing math (pure)
# --------------------------------------------------------------------------


def test_routing_weight_math():
    # healthy + idle: capacity weight dominates
    assert routing_weight(1.0, 2.0, 0) == 2.0
    # load discounts linearly in outstanding work
    assert routing_weight(1.0, 2.0, 3) == pytest.approx(0.5)
    # a degraded light replica loses to a loaded healthy heavy one
    assert routing_weight(0.2, 1.0, 0) < routing_weight(1.0, 4.0, 3)
    # score 0 (not serving) can never win
    assert routing_weight(0.0, 100.0, 0) == 0.0


def test_fleet_config_validation():
    with pytest.raises(ValueError, match="health_floor"):
        FleetConfig(health_floor=1.5)
    with pytest.raises(ValueError, match="drain_failure_threshold"):
        FleetConfig(drain_failure_threshold=0)
    with pytest.raises(ValueError, match="max_failovers"):
        FleetConfig(max_failovers=-1)
    with pytest.raises(ValueError, match="p99_ref_s"):
        FleetConfig(p99_ref_s=0.0)
    with pytest.raises(ValueError, match="tick_s"):
        FleetConfig(tick_s=-1.0)


def test_fault_rule_after_calls():
    plan = FaultPlan([FaultRule(site="s", kind="execute_error", p=1.0,
                                after_calls=2)], seed=0)
    plan.check("s")  # call 0: window closed
    plan.check("s")  # call 1: window closed
    with pytest.raises(Exception):
        plan.check("s")  # call 2: fires
    with pytest.raises(ValueError, match="after_calls"):
        FaultRule(site="s", kind="oom", p=1.0, after_calls=-1)


def test_kill_kind_raises_injected_replica_killed():
    plan = FaultPlan([FaultRule(site="replica", kind="kill", p=1.0)], seed=0)
    with pytest.raises(InjectedReplicaKilled):
        plan.check("replica")


# --------------------------------------------------------------------------
# replica lifecycle state machine
# --------------------------------------------------------------------------


def test_replica_lifecycle_walk():
    rep = Replica("r", FakeExecutorFactory(batch_size=4), serve_config())
    assert rep.state == REPLICA_STARTING
    rep.start()
    assert rep.state == REPLICA_SERVING
    # starting walked through warming (warmup compiles before traffic)
    assert [t for _, _, t in rep.history] == [REPLICA_WARMING,
                                              REPLICA_SERVING]
    assert rep.server.cache.stats()["misses"] == 1  # the warmup build
    rep.drain()
    assert rep.state == REPLICA_DRAINING
    with pytest.raises(ServerClosedError):
        rep.submit("p", height=512, width=512)  # draining: not admitting
    rep.resume()
    assert rep.state == REPLICA_SERVING
    rep.stop()
    assert rep.state == REPLICA_STOPPED
    rep.stop()  # idempotent
    assert rep.state == REPLICA_STOPPED
    # restart: a fresh server generation over the same handle
    rep.start()
    assert rep.state == REPLICA_SERVING and rep.generation == 2
    r = rep.submit("p", height=512, width=512, seed=3).result(timeout=30)
    assert r.replica == "r"
    rep.stop()


def test_replica_illegal_transitions_raise():
    rep = Replica("r", FakeExecutorFactory(batch_size=4), serve_config())
    rep.start()
    with pytest.raises(RuntimeError, match="cannot start"):
        rep.start()  # serving -> warming is not a legal start
    rep.stop()


def test_replica_probe_submit_path():
    rep = Replica("r", FakeExecutorFactory(batch_size=4),
                  serve_config()).start()
    rep.drain()
    # the half-open probe path: a DRAINING replica takes exactly the
    # probe-flagged submit
    r = rep.submit("probe", height=512, width=512, probe=True).result(
        timeout=30)
    assert r.output is not None
    rep.stop()


def test_replica_drain_completes_inflight_work():
    rep = Replica("r", FakeExecutorFactory(batch_size=4, step_time_s=0.05),
                  serve_config()).start()
    futs = [rep.submit(f"p{i}", height=512, width=512, seed=i)
            for i in range(3)]
    rep.drain()  # stop admitting; queued + in-flight work must FINISH
    results = [f.result(timeout=30) for f in futs]
    assert all(r.output is not None for r in results)
    deadline = time.monotonic() + 10
    while not rep.drained and time.monotonic() < deadline:
        time.sleep(0.01)
    assert rep.drained
    rep.stop()


def test_replica_capacity_weight_validation():
    with pytest.raises(ValueError, match="capacity_weight"):
        Replica("r", FakeExecutorFactory(), capacity_weight=0.0)
    with pytest.raises(ValueError, match="name"):
        Replica("", FakeExecutorFactory())


# --------------------------------------------------------------------------
# result pinning (tier / exec key / replica)
# --------------------------------------------------------------------------


def test_serve_result_pins_exec_key_tier_and_replica():
    factory = FakeExecutorFactory(batch_size=4)
    config = serve_config(
        controller=ControllerConfig(enabled=True,
                                    slo_p99_s={"default": 30.0}))
    with InferenceServer(factory, config) as server:
        r = server.submit("p", height=512, width=512).result(timeout=30)
    # bare server: tier pinned to the controller's choice, replica None
    assert r.tier == "full"
    assert r.exec_key == factory.built[0].short()
    assert r.replica is None


def test_fleet_result_pins_replica_name():
    fleet, _ = mk_fleet((("alpha", 1.0),))
    with fleet:
        r = fleet.submit("p", height=512, width=512).result(timeout=30)
    assert r.replica == "alpha"
    assert r.exec_key  # the audit trail always names the executed key


# --------------------------------------------------------------------------
# metrics namespacing (shared registry, per-replica labels)
# --------------------------------------------------------------------------


def test_shared_registry_replica_labels_do_not_collide():
    registry = MetricsRegistry()
    factory_a = FakeExecutorFactory(batch_size=4)
    factory_b = FakeExecutorFactory(batch_size=4)
    with InferenceServer(factory_a, serve_config(), registry=registry,
                         replica_name="a") as sa, \
            InferenceServer(factory_b, serve_config(), registry=registry,
                            replica_name="b") as sb:
        sa.submit("p", height=512, width=512).result(timeout=30)
        sb.submit("p", height=512, width=512).result(timeout=30)
        # each server's SLO view sees only its OWN class windows
        assert set(sa.slo_snapshot()["classes"]) == {"default"}
        assert sa.registry.family("serve_slo_e2e_seconds")[0][0][
            "replica"] == "a"
    fam = registry.family("serve_requests")
    labels = sorted(lbls.get("replica") for lbls, _ in fam)
    assert labels == ["a", "b"]  # two distinct counters, one registry
    # the same metric name without the replica label would have collided
    with pytest.raises(ValueError, match="already registered"):
        registry.gauge("serve_queue_depth", lambda: 0.0,
                       labels={"replica": "a"})


def test_shared_registry_without_replica_name_collides_loudly():
    registry = MetricsRegistry()
    InferenceServer(FakeExecutorFactory(), serve_config(), registry=registry)
    with pytest.raises(ValueError):
        InferenceServer(FakeExecutorFactory(), serve_config(),
                        registry=registry)


def test_scoped_registry_nesting_and_family_filter():
    base = MetricsRegistry()
    scoped = base.scoped({"replica": "r1"}).scoped({"generation": "2"})
    c = scoped.counter("x")
    c.inc("k")
    assert base.get("x", {"replica": "r1", "generation": "2"}) is c
    base.counter("x", labels={"replica": "r2"}).inc("k")
    assert len(base.family("x")) == 2
    assert len(scoped.family("x")) == 1  # filtered to the scope's labels


# --------------------------------------------------------------------------
# fleet routing + failover
# --------------------------------------------------------------------------


def test_one_replica_fleet_parity_with_bare_server():
    """The degenerate 1-replica fleet is behaviorally the bare
    `InferenceServer`: identical outputs for identical (prompt, seed),
    same completion counters, same typed post-stop rejection."""
    prompts = [(f"p{i}", i) for i in range(6)]
    bare_factory = FakeExecutorFactory(batch_size=4)
    with InferenceServer(bare_factory, serve_config()) as server:
        bare = [server.submit(p, height=512, width=512, seed=s).result(
            timeout=30) for p, s in prompts]
    fleet, _ = mk_fleet((("r0", 1.0),))
    with fleet:
        fr = [fleet.submit(p, height=512, width=512, seed=s).result(
            timeout=30) for p, s in prompts]
    for b, f in zip(bare, fr):
        assert (b.output == f.output).all()  # bit-identical generations
        assert b.bucket == f.bucket and b.batch_size >= 1
    snap = fleet.metrics_snapshot()
    assert snap["fleet"]["requests"]["completed"] == len(prompts)
    assert snap["replicas"]["r0"]["requests"]["completed"] == len(prompts)
    with pytest.raises(ServerClosedError):
        fleet.submit("late", height=512, width=512)
    fleet.stop()  # idempotent


def test_failover_executes_exactly_once():
    """A terminal dispatch failure on one replica re-dispatches onto a
    different replica — and the request executes TO COMPLETION exactly
    once, asserted by the shared execution ledger."""
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                p=1.0, max_fires=1)], seed=0)
    cfg = serve_config(resilience=ResilienceConfig(max_retries=0))
    fleet, ledger = mk_fleet(
        (("heavy", 10.0), ("light", 1.0)),  # first dispatch goes to heavy
        config=cfg, fault_plans={"heavy": plan})
    with fleet:
        r = fleet.submit("only", height=512, width=512,
                         seed=7).result(timeout=30)
    assert r.replica == "light"  # failed over off the faulted replica
    assert ledger.count("only", 7) == 1  # never executed twice
    assert ledger.snapshot()[("only", 7)] == ["light"]
    snap = fleet.metrics_snapshot()["fleet"]
    assert snap["requests"]["failovers"] == 1
    assert snap["requests"]["replica_failures"] == 1


def test_failover_budget_exhaustion_surfaces_the_error():
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                p=1.0)], seed=0)
    cfg = serve_config(resilience=ResilienceConfig(max_retries=0))
    fleet, _ = mk_fleet(
        (("r0", 1.0),), config=cfg, fault_plans={"r0": plan},
        fleet_config=FleetConfig(tick_s=0, failover_budget=0,
                                 failover_budget_refill_per_s=0.0,
                                 drain_failure_threshold=100))
    with fleet:
        fut = fleet.submit("p", height=512, width=512)
        with pytest.raises(Exception):
            fut.result(timeout=30)
    snap = fleet.metrics_snapshot()["fleet"]["requests"]
    assert snap.get("failover_budget_exhausted", 0) == 1


def test_heterogeneous_weights_balance_one_slo():
    """Mixed-capability replicas under one fleet: the weighted router
    steers most load to the heavy replica but spills to the light one as
    queues build, and EVERY request completes within its deadline."""
    fleet, ledger = mk_fleet((("heavy", 4.0), ("light", 1.0)),
                             step_time_s=0.01)
    with fleet:
        futs = [fleet.submit(f"p{i}", height=512, width=512, seed=i,
                             ttl_s=30.0) for i in range(20)]
        results = [f.result(timeout=60) for f in futs]
    assert all(r.output is not None for r in results)  # one SLO held
    by_replica = {}
    for executions in ledger.snapshot().values():
        assert len(executions) == 1
        by_replica[executions[0]] = by_replica.get(executions[0], 0) + 1
    # both capacities used, the heavier one more
    assert by_replica.get("heavy", 0) > by_replica.get("light", 0) > 0


def test_auto_drain_and_half_open_reprobe():
    """Fleet-level breaker semantics: a replica failing consecutively is
    auto-drained; after the cooldown exactly one probe routes to it —
    failure re-drains and re-arms, success resumes it."""
    clock = ManualClock()
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                p=1.0, max_fires=3)], seed=0)
    cfg = serve_config(resilience=ResilienceConfig(
        max_retries=0, breaker_failure_threshold=100))
    fleet, ledger = mk_fleet(
        (("flaky", 10.0), ("steady", 1.0)), config=cfg, clock=clock,
        fault_plans={"flaky": plan},
        fleet_config=FleetConfig(tick_s=0, probe_cooldown_s=10.0,
                                 drain_failure_threshold=2,
                                 max_failovers=4))
    with fleet:
        # two terminal failures on "flaky" trip the fleet-level drain;
        # both requests fail over to "steady"
        for i in range(2):
            r = fleet.submit(f"p{i}", height=512, width=512,
                             seed=i).result(timeout=30)
            assert r.replica == "steady"
        assert fleet.replica("flaky").state == REPLICA_DRAINING
        snap = fleet.metrics_snapshot()["fleet"]
        assert snap["requests"]["auto_drains"] == 1
        assert snap["replicas"]["flaky"]["faulted"]
        # cooldown not elapsed: no probe, traffic stays on "steady"
        r = fleet.submit("p2", height=512, width=512, seed=2).result(
            timeout=30)
        assert r.replica == "steady"
        assert fleet.metrics_snapshot()["fleet"]["requests"].get(
            "probes", 0) == 0
        # cooldown elapsed: the next submit is the half-open probe — it
        # fails (one injected fire left), re-drains, and the request
        # still completes elsewhere
        clock.advance(11.0)
        r = fleet.submit("p3", height=512, width=512, seed=3).result(
            timeout=30)
        assert r.replica == "steady"
        snap = fleet.metrics_snapshot()["fleet"]["requests"]
        assert snap["probes"] == 1 and snap["probe_failures"] == 1
        # faults exhausted now: the next probe succeeds and the replica
        # returns to serving
        clock.advance(11.0)
        r = fleet.submit("p4", height=512, width=512, seed=4).result(
            timeout=30)
        assert r.replica == "flaky"
        assert fleet.replica("flaky").state == REPLICA_SERVING
        snap = fleet.metrics_snapshot()["fleet"]["requests"]
        assert snap["probe_successes"] == 1
        # healed: normal traffic routes to it again (heaviest weight)
        r = fleet.submit("p5", height=512, width=512, seed=5).result(
            timeout=30)
        assert r.replica == "flaky"
    assert ledger.max_count() == 1  # across all the failovers and probes


def test_parked_request_redispatches_after_recovery():
    """With no routable replica a failed-over request PARKS in the
    router and re-dispatches from the tick once capacity returns."""
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                p=1.0, max_fires=1)], seed=0)
    cfg = serve_config(resilience=ResilienceConfig(max_retries=0))
    fleet, ledger = mk_fleet(
        (("r0", 1.0), ("r1", 1.0)), config=cfg, fault_plans={"r0": plan},
        fleet_config=FleetConfig(tick_s=0, drain_failure_threshold=1,
                                 probe_cooldown_s=1000.0))
    with fleet:
        fleet.drain_replica("r1")  # manual drain: r0 is the only target
        fut = fleet.submit("p", height=512, width=512, seed=1)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if fleet.metrics_snapshot()["fleet"]["parked"] == 1:
                break
            time.sleep(0.01)
        assert fleet.metrics_snapshot()["fleet"]["parked"] == 1
        assert not fut.done()
        fleet.resume_replica("r1")
        fleet.tick()  # housekeeping re-dispatches the parked request
        r = fut.result(timeout=30)
        assert r.replica == "r1"
    assert ledger.count("p", 1) == 1


def test_no_healthy_replica_is_typed_rejection():
    fleet, _ = mk_fleet((("r0", 1.0),))
    with fleet:
        fleet.drain_replica("r0")
        with pytest.raises(NoHealthyReplicaError):
            fleet.submit("p", height=512, width=512)


def test_fleet_stop_resolves_everything_deterministically():
    """stop() is idempotent and resolves every future: in-flight work
    completes, queued work gets ServerClosedError — across replicas."""
    fleet, _ = mk_fleet((("r0", 1.0), ("r1", 1.0)), step_time_s=0.05)
    fleet.start()
    futs = [fleet.submit(f"p{i}", height=512, width=512, seed=i)
            for i in range(8)]
    fleet.stop(timeout=10.0)
    fleet.stop(timeout=1.0)  # idempotent
    resolved = 0
    for f in futs:
        assert f.done()
        try:
            assert f.result(timeout=0).output is not None
            resolved += 1
        except ServerClosedError:
            pass
    assert resolved >= 1  # the in-flight batches were never abandoned
    with pytest.raises(ServerClosedError):
        fleet.submit("late", height=512, width=512)


def test_stop_during_failover_race():
    """A request mid-failover when stop() lands must still resolve —
    the parked/re-dispatch path checks the stopping flag under the fleet
    lock, so nothing leaks unresolved (the stop-hardening satellite)."""
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                p=1.0)], seed=0)
    cfg = serve_config(resilience=ResilienceConfig(max_retries=0))
    fleet, _ = mk_fleet(
        (("r0", 10.0), ("r1", 1.0)), config=cfg, fault_plans={"r0": plan},
        fleet_config=FleetConfig(tick_s=0, drain_failure_threshold=100))
    entered = threading.Event()
    release = threading.Event()
    orig = FleetRouter._failover

    def gated_failover(self, fr, exc):
        entered.set()
        release.wait(10.0)
        orig(self, fr, exc)

    fleet._failover = types.MethodType(gated_failover, fleet)
    fleet.start()
    fleet.drain_replica("r1")  # failover will find nowhere to go -> park
    fut = fleet.submit("p", height=512, width=512)
    assert entered.wait(10.0)  # r0 failed; the failover is now gated
    stopper = threading.Thread(target=fleet.stop, kwargs={"timeout": 10.0})
    stopper.start()
    time.sleep(0.1)  # let stop() set the stopping flag
    release.set()
    stopper.join(timeout=20.0)
    assert not stopper.is_alive()
    assert fut.done()
    with pytest.raises(ServerClosedError):
        fut.result(timeout=0)


def test_parked_request_expires_at_deadline():
    clock = ManualClock()
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                p=1.0, max_fires=1)], seed=0)
    cfg = serve_config(resilience=ResilienceConfig(max_retries=0))
    fleet, _ = mk_fleet(
        (("r0", 1.0),), config=cfg, clock=clock, fault_plans={"r0": plan},
        fleet_config=FleetConfig(tick_s=0, drain_failure_threshold=1,
                                 probe_cooldown_s=1000.0))
    with fleet:
        fut = fleet.submit("p", height=512, width=512, ttl_s=5.0)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if fleet.metrics_snapshot()["fleet"]["parked"] == 1:
                break
            time.sleep(0.01)
        clock.advance(6.0)  # past the request deadline
        fleet.tick()
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=5)


# --------------------------------------------------------------------------
# the "replica" fault site: kill + recovery
# --------------------------------------------------------------------------


def test_replica_kill_fails_over_and_restart_recovers():
    """The ``kill`` fault stops a replica mid-load: its in-flight and
    queued work fails over (no double execution), the fleet adopts the
    body via auto-drain, and `restart_replica` returns a fresh warmed
    generation to the pool."""
    ledger = ExecutionLedger()
    plan = FaultPlan([FaultRule(site="replica", kind="kill",
                                key_substr="victim", p=1.0, max_fires=1,
                                after_calls=2)], seed=0)
    cfg = serve_config(max_batch_size=2,
                       resilience=ResilienceConfig(max_retries=0))
    registry = MetricsRegistry()
    reps = [
        Replica(name, LedgerFakeExecutorFactory(
            ledger, replica=name, batch_size=2, step_time_s=0.005),
            cfg, capacity_weight=w, fault_plan=plan, registry=registry)
        for name, w in (("victim", 1.0), ("survivor", 1.0))
    ]
    fleet = FleetRouter(reps, FleetConfig(tick_s=0.02), registry=registry)
    with fleet:
        futs = []
        for i in range(16):
            futs.append(fleet.submit(f"p{i}", height=512, width=512, seed=i))
            time.sleep(0.01)
        results = [f.result(timeout=30) for f in futs]
        assert plan.fired() == {"replica/kill": 1}
        assert fleet.replica("victim").killed
        assert fleet.replica("victim").state == REPLICA_STOPPED
        # recovery: a fresh generation, warmed, back in the pool
        fleet.restart_replica("victim")
        assert fleet.replica("victim").state == REPLICA_SERVING
        assert fleet.replica("victim").generation == 2
        assert not fleet.replica("victim").killed
        r = fleet.submit("after", height=512, width=512,
                         seed=99).result(timeout=30)
        assert r.output is not None
    assert all(r.output is not None for r in results)  # 100% availability
    assert ledger.max_count() == 1  # kill + failover never double-executed


def test_redispatch_passes_remaining_ttl_not_a_fresh_one():
    """The client's TTL is one budget across every dispatch: a failover
    (or any re-dispatch) submits with the REMAINING time, and a request
    whose deadline already lapsed is failed, not re-dispatched."""
    from concurrent.futures import Future

    from distrifuser_tpu.serve.fleet import _FleetRequest

    clock = ManualClock()
    fleet, _ = mk_fleet((("r0", 1.0),), clock=clock)
    with fleet:
        captured = {}
        server = fleet.replica("r0").server
        orig_submit = server.submit

        def spy(prompt, **kw):
            captured.update(kw)
            return orig_submit(prompt, **kw)

        server.submit = spy
        params = dict(prompt="x", height=512, width=512,
                      negative_prompt="", num_inference_steps=None,
                      guidance_scale=5.0, seed=0, ttl_s=9.0,
                      slo_class="default")
        # 5 of the 9 TTL seconds already burned on a failed replica:
        # the re-dispatch must carry the remaining 4, not a fresh 9
        fr = _FleetRequest(params=params, future=Future(),
                           deadline=clock() + 4.0)
        ok, _ = fleet._try_dispatch(fr)
        assert ok and captured["ttl_s"] == pytest.approx(4.0)
        fr.future.result(timeout=30)
        # fully lapsed: disposed of with the typed deadline error,
        # never dispatched again
        fr2 = _FleetRequest(params=dict(params), future=Future(),
                            deadline=clock() - 1.0)
        ok, exc = fleet._try_dispatch(fr2)
        assert ok and exc is None
        with pytest.raises(DeadlineExceededError):
            fr2.future.result(timeout=5)


def test_restart_prunes_dead_generation_metrics():
    """A restarted replica's previous server generation leaves the
    shared registry (its gauge closures pinned the dead server); only
    the live generation renders."""
    registry = MetricsRegistry()
    rep = Replica("r", FakeExecutorFactory(batch_size=4), serve_config(),
                  registry=registry)
    rep.start()
    rep.submit("p", height=512, width=512).result(timeout=30)
    gen1 = {"replica": "r", "generation": "1"}
    assert registry.get("serve_requests", gen1) is not None
    rep.stop()
    rep.start()
    assert registry.get("serve_requests", gen1) is None  # pruned
    assert registry.get(
        "serve_requests", {"replica": "r", "generation": "2"}) is not None
    rep.stop()


def test_stop_stays_responsive_during_warmup():
    """start() must not hold the lifecycle lock across the (potentially
    minutes-long) warmup build: a concurrent stop() returns promptly,
    wins the race, and the freshly built server never serves."""
    rep = Replica("r", FakeExecutorFactory(batch_size=4, build_delay_s=0.5),
                  serve_config())
    t = threading.Thread(target=rep.start)
    t.start()
    deadline = time.monotonic() + 5
    while rep.state != REPLICA_WARMING and time.monotonic() < deadline:
        time.sleep(0.01)
    assert rep.state == REPLICA_WARMING
    t0 = time.monotonic()
    rep.stop(timeout=5.0)
    assert time.monotonic() - t0 < 0.4  # did not wait out the 0.5s build
    t.join(timeout=10)
    assert rep.state == REPLICA_STOPPED
    assert rep.server is None  # the discarded server was never published
    with pytest.raises(ServerClosedError):
        rep.submit("p", height=512, width=512)


def test_request_fatal_errors_do_not_drain_healthy_replicas():
    """A client spamming doomed requests (no covering bucket) must not
    auto-drain a healthy fleet: request-fatal outcomes skip the
    consecutive-failure bookkeeping."""
    fleet, _ = mk_fleet(
        (("r0", 1.0), ("r1", 1.0)),
        fleet_config=FleetConfig(tick_s=0, drain_failure_threshold=2))
    with fleet:
        for i in range(6):  # 3x the drain threshold, all NoBucketError
            fut = fleet.submit(f"poison{i}", height=8192, width=8192)
            with pytest.raises(Exception):
                fut.result(timeout=30)
        snap = fleet.metrics_snapshot()["fleet"]
        assert snap["requests"].get("auto_drains", 0) == 0
        assert snap["requests"]["failed_fatal"] == 6
        for entry in snap["replicas"].values():
            assert entry["state"] == REPLICA_SERVING
            assert not entry["faulted"]
        # the fleet still serves real work
        r = fleet.submit("ok", height=512, width=512).result(timeout=30)
        assert r.output is not None


def test_fleet_start_is_parallel():
    """N replicas warm concurrently: fleet startup costs ~one warmup
    build, not N (the warmups are independent compiles)."""
    registry = MetricsRegistry()
    reps = [Replica(f"r{i}", FakeExecutorFactory(batch_size=4,
                                                 build_delay_s=0.3),
                    serve_config(), registry=registry) for i in range(3)]
    fleet = FleetRouter(reps, FleetConfig(tick_s=0), registry=registry)
    t0 = time.monotonic()
    fleet.start()
    elapsed = time.monotonic() - t0
    fleet.stop()
    assert elapsed < 0.75, elapsed  # serial would be >= 0.9


def test_fleet_start_failure_stops_started_replicas():
    """One replica failing to start must not leak the others' scheduler
    threads: the fleet stops what it started and raises."""

    class ExplodingReplica(Replica):
        def start(self):
            raise RuntimeError("injected start failure")

    registry = MetricsRegistry()
    good = Replica("good", FakeExecutorFactory(batch_size=4),
                   serve_config(), registry=registry)
    bad = ExplodingReplica("bad", FakeExecutorFactory(batch_size=4),
                           serve_config(), registry=registry)
    fleet = FleetRouter([good, bad], FleetConfig(tick_s=0),
                        registry=registry)
    with pytest.raises(RuntimeError, match="failed to start"):
        fleet.start()
    assert good.state == REPLICA_STOPPED  # cleaned up, not leaked


def test_kill_is_terminal_even_with_retries_enabled():
    """The kill signals the server's shutdown SYNCHRONOUSLY before the
    fault propagates, so the in-server retry loop can never re-dispatch
    onto the "dead" replica and mask the kill — the batch fails
    terminally and the fleet fails over, deterministically."""
    ledger = ExecutionLedger()
    plan = FaultPlan([FaultRule(site="replica", kind="kill",
                                key_substr="victim", p=1.0, max_fires=1)],
                     seed=0)
    cfg = serve_config(resilience=ResilienceConfig(
        max_retries=5, backoff_base_s=0.001, backoff_max_s=0.01))
    registry = MetricsRegistry()
    reps = [
        Replica(name, LedgerFakeExecutorFactory(
            ledger, replica=name, batch_size=4), cfg,
            capacity_weight=w, fault_plan=plan, registry=registry)
        for name, w in (("victim", 10.0), ("survivor", 1.0))
    ]
    fleet = FleetRouter(reps, FleetConfig(tick_s=0), registry=registry)
    with fleet:
        r = fleet.submit("only", height=512, width=512,
                         seed=1).result(timeout=30)
    assert r.replica == "survivor"
    assert ledger.snapshot()[("only", 1)] == ["survivor"]
    assert plan.fired() == {"replica/kill": 1}  # retries never re-fired it
    assert fleet.replica("victim").killed


def test_rebuilt_fleet_over_same_replicas_and_registry():
    """stop()'s error message says 'build a new FleetRouter' — that
    recovery path must actually work over the same replicas and shared
    registry (the new router replaces its predecessor's fleet gauges
    instead of colliding)."""
    registry = MetricsRegistry()
    reps = [Replica(f"r{i}", FakeExecutorFactory(batch_size=4),
                    serve_config(), registry=registry) for i in range(2)]
    fleet1 = FleetRouter(reps, FleetConfig(tick_s=0), registry=registry)
    with fleet1:
        fleet1.submit("p", height=512, width=512).result(timeout=30)
    with pytest.raises(ServerClosedError, match="build a new"):
        fleet1.start()
    fleet2 = FleetRouter(reps, FleetConfig(tick_s=0), registry=registry)
    with fleet2:
        r = fleet2.submit("q", height=512, width=512).result(timeout=30)
        assert r.output is not None
        # double start is a typed caller error, never a teardown
        with pytest.raises(RuntimeError, match="already started"):
            fleet2.start()
        assert all(s.replica.state == REPLICA_SERVING
                   for s in fleet2._slots.values())


def test_auto_restart_cannot_resurrect_after_stop():
    """A pending auto-restart must not bring a replica back to life
    after the fleet stopped — the restart path checks the stopping
    latch (the leaked-scheduler-thread hazard)."""
    fleet, _ = mk_fleet(
        (("r0", 1.0),),
        fleet_config=FleetConfig(tick_s=0, auto_restart=True,
                                 restart_cooldown_s=0.0))
    fleet.start()
    slot = fleet._slots["r0"]
    fleet.stop()
    fleet._restart_async(slot)  # what a racing tick would have spawned
    deadline = time.monotonic() + 5
    while slot.restarting and time.monotonic() < deadline:
        time.sleep(0.01)
    assert fleet.replica("r0").state == REPLICA_STOPPED
    assert not slot.restarting
    # the operator paths share the same latch
    with pytest.raises(ServerClosedError):
        fleet.restart_replica("r0")
    with pytest.raises(ServerClosedError):
        fleet.drain_replica("r0")
    assert fleet.replica("r0").state == REPLICA_STOPPED


def test_fleet_health_snapshot_shape():
    fleet, _ = mk_fleet((("a", 1.0), ("b", 2.0)))
    with fleet:
        fleet.submit("p", height=512, width=512).result(timeout=30)
        h = fleet.health()
        assert h["status"] == "ok"
        assert h["serving_replicas"] == 2 and h["total_replicas"] == 2
        assert set(h["replicas"]) == {"a", "b"}
        for entry in h["replicas"].values():
            assert entry["state"] == REPLICA_SERVING
            assert 0.0 <= entry["score"] <= 1.0
        import json

        json.dumps(fleet.metrics_snapshot())  # JSON end to end
        json.dumps(h)

"""Comm/compute overlap: structural verification from compiled HLO.

The claim under test (runner.py docstring, SURVEY.md §3.3): in the stale
steady-state scan, every refresh collective (halo ppermute, KV all-gather)
produces values consumed only by the *next* iteration, so the scheduler can
hide them behind compute — the role of the reference's async NCCL gathers
(/root/reference/distrifuser/utils.py:170-190).  The sync/full_sync path is
the negative control: its gathers feed attention in the same step and MUST
classify as inline, proving the analysis discriminates.
"""

import jax
import jax.numpy as jnp
import pytest

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models import unet as unet_mod
from distrifuser_tpu.parallel.runner import DenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.utils.overlap import analyze_loop_collectives


def _compiled_hlo(devices8, mode, num_steps):
    ucfg = unet_mod.tiny_config(sdxl=False)
    params = unet_mod.init_unet_params(jax.random.PRNGKey(0), ucfg)
    depth = len(ucfg.block_out_channels) - 1
    cfg = DistriConfig(
        devices=devices8, height=8 * 8 * (1 << depth) * 2, width=128,
        warmup_steps=1, parallelism="patch", mode=mode,
    )
    runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    lat = jnp.zeros((1, cfg.latent_height, cfg.latent_width, ucfg.in_channels))
    enc = jnp.zeros((2, 1, 7, ucfg.cross_attention_dim))
    fn = runner._build(num_steps)
    return fn.lower(params, lat, enc, None, 5.0).compile().as_text()


def test_stale_scan_collectives_all_deferred(devices8):
    """Steady state: every refresh collective must be carry-only; the only
    same-step consumers allowed are the full-output gather + CFG combine
    (synchronous in the reference as well, distri_sdxl_unet_pp.py:162-169)."""
    hlo = _compiled_hlo(devices8, "corrected_async_gn", 4)
    reports = analyze_loop_collectives(hlo)
    assert reports, "no while-loop collectives found in patch program"
    # with warmup_steps=1 and 4 steps the only surviving loop is the stale scan
    stale = max(reports, key=lambda r: r.n_deferred)
    assert stale.n_inline <= 2, (
        f"stale-scan refresh collectives serialize against compute: {stale.inline}"
    )
    assert all(k.startswith("all-gather") for k in stale.inline.values()), (
        f"only the output/CFG gathers may be inline, got {stale.inline}"
    )
    # the refresh set: per-conv halo permutes + per-self-attn KV gathers
    kinds = set(stale.deferred.values())
    assert "collective-permute" in kinds, "halo refreshes missing from carry"
    assert any(k.startswith("all-gather") for k in kinds), (
        "KV refreshes missing from carry"
    )
    assert stale.n_deferred >= 10


def test_sync_path_collectives_are_inline(devices8):
    """Negative control: full_sync gathers feed same-step attention compute —
    the analyzer must NOT classify them as overlappable."""
    hlo = _compiled_hlo(devices8, "full_sync", 5)
    reports = analyze_loop_collectives(hlo)
    assert reports, "no while-loop collectives found in full_sync program"
    body = max(reports, key=lambda r: r.n_inline)
    assert body.n_inline > 0, (
        "analysis lost discrimination: sync-phase gathers classified deferred"
    )


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

"""Serve <-> pipeline integration: the real executor adapter on a tiny
random-weight SD pipeline (CPU, fake mesh), plus the pre-bucketed
generate_batch entry and the serve_bench artifact contract."""

import json
import sys

import numpy as np
import pytest

from distrifuser_tpu.serve import ExecKey, InferenceServer, ServeConfig
from distrifuser_tpu.serve.executors import (
    PipelineExecutor,
    pipeline_executor_factory,
)

from test_pipelines import build_sd_pipeline


def test_generate_batch_requires_exact_batch_size(devices8):
    pipe, dcfg = build_sd_pipeline(devices8, 1, batch_size=2)
    with pytest.raises(ValueError, match="pre-bucketed"):
        pipe.generate_batch(["one"], num_inference_steps=2)
    with pytest.raises(ValueError, match="num_images_per_prompt"):
        pipe.generate_batch(["a", "b"], num_inference_steps=2,
                            num_images_per_prompt=2)


def test_generate_batch_matches_call(devices8):
    """The pre-bucketed entry is the same code path as __call__: identical
    outputs for identical inputs."""
    pipe, _ = build_sd_pipeline(devices8, 1, batch_size=2)
    kw = dict(num_inference_steps=2, seed=5, output_type="np")
    a = pipe(["a cat", "a dog"], **kw)
    b = pipe.generate_batch(["a cat", "a dog"], **kw)
    np.testing.assert_array_equal(np.stack(a.images), np.stack(b.images))


def test_pipeline_executor_chunks_wide_batches(devices8):
    """A coalesced batch wider than the compiled batch width runs as
    several exactly-batch_size invocations — per-request outputs identical
    to a narrow run (no contract error, no retrace)."""
    pipe, _ = build_sd_pipeline(devices8, 1, batch_size=2)
    ex = PipelineExecutor(pipe, steps=2)
    wide = ex(["a cat"] * 3, [""] * 3, 5.0, seeds=[1, 2, 3])
    assert len(wide) == 3
    narrow = ex(["a cat"], [""], 5.0, seeds=[3])
    np.testing.assert_array_equal(wide[2], narrow[0])


def test_pipeline_executor_honors_per_request_seeds(devices8):
    """Coalescing must not change a request's image: executor outputs for
    (prompt, seed) match the same request run alone."""
    pipe, _ = build_sd_pipeline(devices8, 1, batch_size=2)
    ex = PipelineExecutor(pipe, steps=2)
    batched = ex(["a cat", "a cat"], ["", ""], 5.0, seeds=[3, 9])
    alone = ex(["a cat"], [""], 5.0, seeds=[3])  # pads to batch 2 internally
    np.testing.assert_array_equal(batched[0], alone[0])
    assert np.abs(np.asarray(batched[0]) - np.asarray(batched[1])).max() > 0


def test_stepwise_fallback_key_matches_fused(devices8):
    """The degradation ladder's ``exec_mode='stepwise'`` key
    (serve/resilience.py, applied by executors.apply_key_policy via
    pipelines.set_stepwise) is the fused scan's numerics within the
    repo's fused-vs-stepwise parity tolerance (test_stepwise.py) — the
    fallback degrades dispatch granularity, never image quality."""
    import dataclasses

    def build(key: ExecKey):
        pipe, _ = build_sd_pipeline(
            devices8, 1, height=key.height, width=key.width, batch_size=2,
            do_classifier_free_guidance=key.cfg,
        )
        return pipe

    factory = pipeline_executor_factory(build)
    key = ExecKey(model_id="t", scheduler="ddim", height=128, width=128,
                  steps=2, cfg=True, mesh_plan="dp1.cfg1.sp1")
    fused = factory(key)
    stepwise = factory(dataclasses.replace(key, exec_mode="stepwise"))
    assert fused.pipeline.distri_config.use_compiled_step
    assert not stepwise.pipeline.distri_config.use_compiled_step
    a = fused(["a cat"], [""], 5.0, seeds=[3])
    b = stepwise(["a cat"], [""], 5.0, seeds=[3])
    np.testing.assert_allclose(np.asarray(a[0]), np.asarray(b[0]), atol=2e-4)


def test_runner_compile_global_fault_site(devices8):
    """The process-global chaos hook (utils/chaos.py, re-exported by
    serve.faults) fires under DenoiseRunner.compiled_handle: prepare()
    fails deterministically once, then builds clean."""
    from distrifuser_tpu.serve import FaultPlan, FaultRule, install_fault_plan

    pipe, _ = build_sd_pipeline(devices8, 1, batch_size=2)
    install_fault_plan(FaultPlan([FaultRule(
        site="runner.compile", kind="compile_error", at_calls=(0,))]))
    try:
        with pytest.raises(Exception, match="injected compile_error"):
            pipe.prepare(2)
        pipe.prepare(2)  # the rule fired once; the rebuild succeeds
    finally:
        install_fault_plan(None)
    out = pipe(["a cat", "a dog"], num_inference_steps=2, seed=1,
               output_type="latent")
    assert len(out.images) == 2


def test_server_over_real_pipeline(devices8):
    """Full stack: submit -> bucket snap -> cache build (prepare) ->
    batched execution -> per-request results."""
    def build_pipeline(key: ExecKey):
        pipe, _ = build_sd_pipeline(
            devices8, 1, height=key.height, width=key.width, batch_size=2,
            do_classifier_free_guidance=key.cfg,
        )
        return pipe

    config = ServeConfig(
        max_queue_depth=8, max_batch_size=2, batch_window_s=0.2,
        buckets=((128, 128),), default_steps=2, cache_capacity=2,
    )
    factory = pipeline_executor_factory(build_pipeline)
    with InferenceServer(factory, config, model_id="tiny-sd",
                         scheduler="ddim", mesh_plan="dp1.cfg1.sp1") as server:
        f1 = server.submit("a cat", height=128, width=128, seed=1)
        f2 = server.submit("a dog", height=96, width=96, seed=2)
        r1, r2 = f1.result(timeout=600), f2.result(timeout=600)
    assert r1.bucket == r2.bucket == (128, 128)  # 96x96 snapped up
    assert r1.output.shape == r2.output.shape  # bucket-resolution outputs
    assert np.isfinite(r1.output).all()
    snap = server.metrics_snapshot()
    assert snap["requests"]["completed"] == 2
    assert snap["cache"]["misses"] == 1  # one bucket, one compile


def test_serve_bench_dry_run_artifact(tmp_path):
    """scripts/serve_bench.py --dry-run emits a well-formed JSON artifact."""
    sys.path.insert(0, "scripts")
    import serve_bench

    out = tmp_path / "artifact.json"
    rc = serve_bench.main([
        "--dry-run", "--mode", "closed", "--requests", "8",
        "--concurrency", "4", "--fake_build_s", "0", "--fake_step_s", "0",
        "--out", str(out),
    ])
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["bench"]["backend"] == "dry-run"
    assert art["load"]["completed"] == 8
    m = art["metrics"]
    assert m["requests"]["completed"] == 8
    assert m["cache"]["hits"] + m["cache"]["misses"] >= 1
    for hist in m["latency_s"].values():
        assert hist["count"] == 8

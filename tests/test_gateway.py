"""distrigate (distrifuser_tpu/serve/gateway.py + tenancy.py +
httpbase.py): HTTP/SSE round-trip byte-identical to in-process submit,
per-tenant token-bucket quotas (typed 429), weighted deficit-round-robin
fairness and starvation-freedom in the queue, SSE backpressure
(drop-oldest, counted, never blocks), fleet-fronted failover through the
gateway, deterministic stop resolving every open stream, and the shared
HTTP host's immediate-rebind fix."""

import json
import time
import urllib.error
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from distrifuser_tpu.serve import (
    FleetConfig,
    FleetRouter,
    Gateway,
    GatewayConfig,
    HTTPServerHost,
    InferenceServer,
    MetricsRegistry,
    Replica,
    ResilienceConfig,
    ServeConfig,
    StepBatchConfig,
    TenancyPolicy,
    TenantConfig,
    TenantQuotaError,
    decode_image,
)
from distrifuser_tpu.serve.faults import FaultPlan, FaultRule
from distrifuser_tpu.serve.gateway import _GatewayRequest, sse_format
from distrifuser_tpu.serve.queue import Request, RequestQueue
from distrifuser_tpu.serve.tenancy import TokenBucket
from distrifuser_tpu.serve.testing import (
    ExecutionLedger,
    LedgerFakeExecutorFactory,
    StepFakeExecutorFactory,
)
from distrifuser_tpu.utils import sync


def serve_config(**kw):
    kw.setdefault("max_queue_depth", 64)
    kw.setdefault("batch_window_s", 0.001)
    kw.setdefault("buckets", ((64, 64),))
    kw.setdefault("warmup_buckets", ())
    kw.setdefault("default_steps", 4)
    kw.setdefault("default_ttl_s", 60.0)
    kw.setdefault("step_batching",
                  StepBatchConfig(enabled=True, slots=4,
                                  preview_interval=1))
    kw.setdefault("gateway", GatewayConfig(port=0))
    return ServeConfig(**kw)


def mk_request(prompt="p", steps=1, tenant="default", ttl=60.0, seed=0):
    now = time.monotonic()
    return Request(prompt=prompt, height=64, width=64,
                   num_inference_steps=steps, deadline=now + ttl,
                   seed=seed, tenant=tenant, enqueue_ts=now)


def post_json(url, body, timeout=15):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def read_sse(url, timeout=30):
    """Drain one SSE stream into a [(event_name, data_dict)] list."""
    events = []
    with urllib.request.urlopen(url, timeout=timeout) as r:
        name = None
        for line in r:
            line = line.decode().rstrip("\n")
            if line.startswith("event: "):
                name = line[7:]
            elif line.startswith("data: "):
                events.append((name, json.loads(line[6:])))
    return events


class StubBackend:
    """submit() -> a Future the test resolves (or doesn't) by hand."""

    def __init__(self):
        self.calls = []

    def submit(self, prompt, **kw):
        f = Future()
        self.calls.append((prompt, kw, f))
        return f


# --------------------------------------------------------------------------
# token bucket + tenancy policy units
# --------------------------------------------------------------------------


def test_token_bucket_refill_and_burst():
    t = [0.0]
    b = TokenBucket(rate=2.0, burst=2.0, clock=lambda: t[0])
    assert b.try_take() and b.try_take()   # burst drained
    assert not b.try_take()
    t[0] = 0.5                             # 2/s -> one token back
    assert b.try_take()
    assert not b.try_take()
    t[0] = 100.0                           # refill caps at burst
    assert b.try_take() and b.try_take() and not b.try_take()


def test_unlimited_bucket_never_rejects():
    b = TokenBucket(rate=0.0, burst=0.0, clock=lambda: 0.0)
    assert all(b.try_take() for _ in range(1000))


def test_tenant_config_validation():
    with pytest.raises(ValueError, match="weight"):
        TenantConfig(name="a", weight=0.0)
    with pytest.raises(ValueError, match="rate_rps"):
        TenantConfig(name="a", rate_rps=-1.0)
    with pytest.raises(ValueError, match="name"):
        TenantConfig(name="")
    # rate without burst gets a sane burst, not a dead bucket
    assert TenantConfig(name="a", rate_rps=3.0).burst == 3.0
    with pytest.raises(ValueError, match="duplicate"):
        GatewayConfig(tenants=(TenantConfig(name="a"),
                               TenantConfig(name="a")))


def test_quota_rejection_is_typed_and_counted():
    cfg = GatewayConfig(tenants=(
        TenantConfig(name="t", rate_rps=0.001, burst=2.0),))
    t = [0.0]
    pol = TenancyPolicy(cfg, clock=lambda: t[0])
    pol.admit(mk_request(tenant="t"))
    pol.admit(mk_request(tenant="t"))
    with pytest.raises(TenantQuotaError):
        pol.admit(mk_request(tenant="t"))
    with pytest.raises(TenantQuotaError, match="unknown tenant"):
        pol.admit(mk_request(tenant="nobody"))
    snap = pol.snapshot()
    assert snap["t"]["admitted"] == 2
    assert snap["t"]["rejected_quota"] == 1


def test_drr_share_follows_weights():
    """Both tenants backlogged, weight 2:1, unit cost -> dequeue ratio
    is exactly the weight ratio over full DRR rotations."""
    cfg = GatewayConfig(tenants=(TenantConfig(name="a", weight=2.0),
                                 TenantConfig(name="b", weight=1.0)),
                        drr_quantum=4.0)
    q = RequestQueue(max_depth=256, policy=TenancyPolicy(
        cfg, clock=lambda: 0.0))
    for i in range(36):
        q.put(mk_request(prompt=f"a{i}", tenant="a", steps=1))
        q.put(mk_request(prompt=f"b{i}", tenant="b", steps=1))
    order = []
    score = lambda r: r.deadline  # noqa: E731 — EDF stand-in
    for _ in range(24):
        pick = q.peek_best(score)
        assert q.remove(pick)
        order.append(pick.tenant)
    assert order.count("a") == 16 and order.count("b") == 8


def test_drr_peek_is_idempotent_until_charged():
    """peek_best N times without removing advances nothing: same winner
    every time, and the round only commits at remove()."""
    cfg = GatewayConfig(tenants=(TenantConfig(name="a"),
                                 TenantConfig(name="b")))
    q = RequestQueue(max_depth=16, policy=TenancyPolicy(
        cfg, clock=lambda: 0.0))
    for tn in ("a", "b"):
        for i in range(3):
            q.put(mk_request(prompt=f"{tn}{i}", tenant=tn, steps=1))
    first = q.peek_best(lambda r: r.deadline)
    for _ in range(5):
        assert q.peek_best(lambda r: r.deadline) is first
    assert q.remove(first)
    assert q.peek_best(lambda r: r.deadline) is not first


def test_drr_starvation_freedom_under_burst():
    """A 40-request burst from one tenant cannot starve the other: at
    equal weight the steady tenant's 5 requests all leave within the
    first ~2x5 dequeues-worth of its share, far before the burst
    drains."""
    cfg = GatewayConfig(tenants=(TenantConfig(name="burst"),
                                 TenantConfig(name="steady")),
                        drr_quantum=4.0)
    q = RequestQueue(max_depth=64, policy=TenancyPolicy(
        cfg, clock=lambda: 0.0))
    for i in range(40):
        q.put(mk_request(prompt=f"burst{i}", tenant="burst", steps=1))
    for i in range(5):
        q.put(mk_request(prompt=f"steady{i}", tenant="steady", steps=1))
    drained_at = []
    score = lambda r: r.request_id  # noqa: E731 — FIFO-ish
    for n in range(45):
        pick = q.peek_best(score)
        assert q.remove(pick)
        if pick.tenant == "steady":
            drained_at.append(n)
    assert len(drained_at) == 5
    # without DRR the steady tenant would wait out all 40 burst items;
    # with equal shares its last request leaves by ~2x its own count
    assert drained_at[-1] <= 16


def test_peek_urgent_sees_past_the_drr_cursor():
    """The deadline-rescue path must see the globally tightest request
    even while the DRR cursor camps on a backlogged tenant's turn:
    peek_best (fair share) proposes the cursor tenant, peek_urgent
    (rescue) the other tenant's about-to-miss request — hiding it
    behind turn continuity would make preemption blind exactly when a
    flood fills every slot."""
    cfg = GatewayConfig(tenants=(TenantConfig(name="burst"),
                                 TenantConfig(name="steady")),
                        drr_quantum=4.0)
    q = RequestQueue(max_depth=64, policy=TenancyPolicy(
        cfg, clock=lambda: 0.0))
    for i in range(8):
        q.put(mk_request(prompt=f"burst{i}", tenant="burst", steps=1,
                         ttl=60.0))
    score = lambda r: r.deadline  # noqa: E731 — EDF stand-in
    # serve one burst request: the cursor parks ON burst (turn
    # continuity) with deficit left to keep serving it
    first = q.peek_best(score)
    assert first.tenant == "burst" and q.remove(first)
    q.put(mk_request(prompt="tight", tenant="steady", steps=1, ttl=0.5))
    fair = q.peek_best(score)
    urgent = q.peek_urgent(score)
    assert fair.tenant == "burst"  # the share-fair pick: burst's turn
    assert urgent.tenant == "steady" and urgent.prompt == "tight"
    # removing the rescued request still accounts to its tenant via the
    # charge fallback, and the fair pick is unchanged afterwards
    assert q.remove(urgent)
    assert q.tenancy_snapshot()["steady"]["dequeued"] == 1
    assert q.peek_best(score).tenant == "burst"


def test_idle_tenant_forfeits_deficit():
    """DRR deficit does not accumulate while a tenant has nothing
    queued — an idle tenant returns with zero credit, not a stockpile."""
    cfg = GatewayConfig(tenants=(TenantConfig(name="a"),
                                 TenantConfig(name="b")))
    pol = TenancyPolicy(cfg, clock=lambda: 0.0)
    q = RequestQueue(max_depth=16, policy=pol)
    q.put(mk_request(tenant="a", steps=1))
    pick = q.peek_best(lambda r: r.deadline)
    assert q.remove(pick)   # queue now empty: everyone idle
    snap = pol.snapshot()
    assert snap["a"]["deficit"] == 0.0
    assert snap["b"]["deficit"] == 0.0


def test_quota_checked_before_depth():
    """A flooding tenant burns ITS budget, not the shared depth: the
    quota rejection fires even when the queue itself still has room."""
    cfg = GatewayConfig(tenants=(
        TenantConfig(name="t", rate_rps=0.001, burst=1.0),))
    q = RequestQueue(max_depth=100, policy=TenancyPolicy(
        cfg, clock=lambda: 0.0))
    q.put(mk_request(tenant="t", steps=1))
    with pytest.raises(TenantQuotaError):
        q.put(mk_request(tenant="t", steps=1))
    assert len(q) == 1


# --------------------------------------------------------------------------
# SSE event buffer: backpressure without blocking
# --------------------------------------------------------------------------


def test_event_buffer_drops_oldest_and_counts():
    gr = _GatewayRequest("r", "t", max_events=4, clock=lambda: 0.0)
    for i in range(10):
        gr.push("preview", {"step": i})
    assert gr.dropped == 6
    evs, done = gr.next_events(-1, timeout=0)
    assert not done
    assert [d["step"] for _, _, d in evs] == [6, 7, 8, 9]
    # sequence numbers expose the gap (consumer can see it dropped)
    assert [s for s, _, _ in evs] == [6, 7, 8, 9]


def test_terminal_event_never_dropped():
    gr = _GatewayRequest("r", "t", max_events=2, clock=lambda: 0.0)
    for i in range(5):
        gr.push("preview", {"step": i})
    assert gr.finish("final", {"id": "r"}, outcome="completed",
                     result={"id": "r"})
    evs, done = gr.next_events(-1, timeout=0)
    assert done
    assert evs[-1][1] == "final"
    # exactly-one-terminal: a racing second terminal loses cleanly
    assert not gr.finish("cancelled", {}, outcome="cancelled")
    assert gr.outcome == "completed"
    # post-terminal pushes are discarded
    assert gr.push("preview", {"step": 99}) == 0


def test_push_never_blocks_on_absent_consumer():
    """The scheduler-thread contract: pushing thousands of events with
    nobody draining completes quickly (bounded buffer, no waits)."""
    gr = _GatewayRequest("r", "t", max_events=8, clock=lambda: 0.0)
    t0 = time.monotonic()
    for i in range(5000):
        gr.push("preview", {"step": i})
    assert time.monotonic() - t0 < 2.0
    assert gr.dropped == 5000 - 8


def test_sse_wire_format():
    chunk = sse_format("preview", {"step": 1})
    assert chunk == b'event: preview\ndata: {"step": 1}\n\n'


# --------------------------------------------------------------------------
# gateway core over a stub backend (no sockets)
# --------------------------------------------------------------------------


def test_generate_validation_errors_are_400():
    gw = Gateway(StubBackend())
    assert gw.handle_generate(["not", "an", "object"])[0] == 400
    assert gw.handle_generate({})[0] == 400                    # no prompt
    assert gw.handle_generate({"prompt": ""})[0] == 400
    assert gw.handle_generate({"prompt": "p", "steps": 0})[0] == 400
    assert gw.handle_generate({"prompt": "p", "steps": "x"})[0] == 400
    assert gw.handle_generate({"prompt": "p", "deadline": -1})[0] == 400
    status, body = gw.handle_generate({"prompt": "p"})
    assert status == 202 and body["id"]


def test_unknown_id_is_404():
    gw = Gateway(StubBackend())
    assert gw.handle_status("nope")[0] == 404
    assert gw.handle_cancel("nope")[0] == 404
    with pytest.raises(KeyError):
        gw.next_events("nope")


def test_cancel_maps_to_future_cancel_exactly_one_terminal():
    backend = StubBackend()
    gw = Gateway(backend)
    _, sub = gw.handle_generate({"prompt": "p"})
    rid = sub["id"]
    _, cres = gw.handle_cancel(rid)
    assert cres["cancelled"] is True
    _, _, fut = backend.calls[0]
    assert fut.cancelled()
    evs, done = gw.next_events(rid, -1, timeout=0)
    assert done
    assert [n for _, n, _ in evs] == ["queued", "cancelled"]
    # a second cancel is a no-op report, not a second terminal event
    _, cres2 = gw.handle_cancel(rid)
    assert cres2["cancelled"] is False
    assert cres2["status"] == "cancelled"
    evs2, _ = gw.next_events(rid, -1, timeout=0)
    assert len(evs2) == len(evs)


def test_cancel_after_completion_loses_race():
    backend = StubBackend()
    gw = Gateway(backend)
    _, sub = gw.handle_generate({"prompt": "p"})
    fut = backend.calls[0][2]

    class R:  # minimal ServeResult stand-in
        output = np.zeros((2, 2, 3), np.float32)
        queue_wait_s = execute_s = e2e_s = 0.0
        batch_size = 1
        compile_hit = True
        exec_key = "k"
        tier = replica = None
        previews = 0
        first_preview_s = None
        preempts = 0

    fut.set_result(R())
    _, cres = gw.handle_cancel(sub["id"])
    assert cres["cancelled"] is False and cres["status"] == "completed"
    evs, done = gw.next_events(sub["id"], -1, timeout=0)
    assert done and [n for _, n, _ in evs] == ["queued", "final"]


def test_backend_rejection_maps_to_http_status():
    from distrifuser_tpu.serve import QueueFullError

    class Rejecting:
        def submit(self, prompt, **kw):
            raise QueueFullError("full")

    gw = Gateway(Rejecting())
    status, body = gw.handle_generate({"prompt": "p"})
    assert status == 429
    assert body["error"] == "QueueFullError" and body["retryable"]


def test_stop_resolves_every_open_stream():
    """Readers blocked in next_events on PENDING requests all terminate
    once stop() runs — no stranded stream, no backend help needed."""
    backend = StubBackend()
    gw = Gateway(backend)
    rids = [gw.handle_generate({"prompt": f"p{i}"})[1]["id"]
            for i in range(4)]
    finished = []
    lock = sync.Lock()

    def reader(rid):
        cursor = -1
        while True:
            evs, resolved = gw.next_events(rid, cursor, timeout=0.1)
            for seq, _, _ in evs:
                cursor = seq
            if resolved and not evs:
                break
        with lock:
            finished.append(rid)

    threads = [sync.Thread(target=reader, args=(rid,)) for rid in rids]
    for t in threads:
        t.start()
    time.sleep(0.05)   # readers are parked waiting on events
    gw.stop()
    for t in threads:
        t.join(timeout=5)
        assert not t.is_alive()
    assert sorted(finished) == sorted(rids)
    # draining gateway refuses new work with a typed 503
    status, body = gw.handle_generate({"prompt": "late"})
    assert status == 503 and body["error"] == "ServerClosedError"


# --------------------------------------------------------------------------
# full HTTP round trips against a live server
# --------------------------------------------------------------------------


def test_http_generation_byte_identical_to_inprocess():
    cfg = serve_config(gateway=GatewayConfig(port=0, tenants=(
        TenantConfig(name="a", weight=2.0),
        TenantConfig(name="b", weight=1.0),)))
    with InferenceServer(StepFakeExecutorFactory(batch_size=4),
                         cfg) as srv:
        base = srv.gateway_endpoint.url
        status, sub = post_json(base + "/v1/generate", {
            "prompt": "hello", "steps": 4, "seed": 7, "height": 64,
            "width": 64, "tenant": "a"})
        assert status == 202
        events = read_sse(base + sub["events"])
        names = [n for n, _ in events]
        assert names[0] == "queued" and names[-1] == "final"
        assert names.count("preview") >= 1
        final = events[-1][1]
        img = decode_image(final)
        ref = srv.submit("hello", height=64, width=64,
                         num_inference_steps=4, seed=7,
                         tenant="a").result(timeout=30)
        assert img.tobytes() == np.asarray(ref.output).tobytes()
        assert img.dtype == np.asarray(ref.output).dtype
        # previews carry step progress and decode too
        pv = [d for n, d in events if n == "preview"][0]
        assert pv["total_steps"] == 4 and decode_image(pv).ndim == 3
        # final carries the lifecycle metrics the bench consumes
        assert final["metrics"]["previews"] >= 1
        assert final["metrics"]["queue_wait_s"] >= 0.0
        # poll endpoint agrees after the fact
        with urllib.request.urlopen(base + sub["poll"], timeout=5) as r:
            st = json.loads(r.read())
        assert st["status"] == "completed"
        snap = srv.metrics_snapshot()
        assert snap["tenancy"]["a"]["admitted"] >= 2


def test_http_tenant_quota_is_429():
    cfg = serve_config(gateway=GatewayConfig(port=0, tenants=(
        TenantConfig(name="t", rate_rps=0.001, burst=1.0),)))
    with InferenceServer(StepFakeExecutorFactory(batch_size=4),
                         cfg) as srv:
        base = srv.gateway_endpoint.url
        status, sub = post_json(base + "/v1/generate", {
            "prompt": "ok", "height": 64, "width": 64, "steps": 2,
            "tenant": "t"})
        assert status == 202
        with pytest.raises(urllib.error.HTTPError) as ei:
            post_json(base + "/v1/generate", {
                "prompt": "over", "height": 64, "width": 64, "steps": 2,
                "tenant": "t"})
        assert ei.value.code == 429
        body = json.loads(ei.value.read())
        assert body["error"] == "TenantQuotaError" and body["retryable"]
        # unknown tenant is the same typed rejection
        with pytest.raises(urllib.error.HTTPError) as ei2:
            post_json(base + "/v1/generate", {
                "prompt": "who", "height": 64, "width": 64,
                "tenant": "stranger"})
        assert ei2.value.code == 429
        # the admitted request still completes normally
        events = read_sse(base + sub["events"])
        assert events[-1][0] == "final"
        assert srv.counters.get("rejected_tenant_quota") == 2


def test_http_bad_json_and_unknown_routes():
    cfg = serve_config()
    with InferenceServer(StepFakeExecutorFactory(batch_size=4),
                         cfg) as srv:
        base = srv.gateway_endpoint.url
        req = urllib.request.Request(
            base + "/v1/generate", data=b"{not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei2:
            urllib.request.urlopen(base + "/v1/nope", timeout=5)
        assert ei2.value.code == 404
        # health passthrough from the backend
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert json.loads(r.read())["scheduler_alive"]


def test_http_cancel_round_trip():
    """Submit two, cancel the second before it can run; its stream ends
    in exactly one terminal `cancelled` event."""
    cfg = serve_config(
        step_batching=StepBatchConfig(enabled=True, slots=1,
                                      preview_interval=1))
    factory = StepFakeExecutorFactory(batch_size=1, step_time_s=0.02)
    with InferenceServer(factory, cfg) as srv:
        base = srv.gateway_endpoint.url
        _, first = post_json(base + "/v1/generate", {
            "prompt": "long", "steps": 40, "height": 64, "width": 64})
        _, second = post_json(base + "/v1/generate", {
            "prompt": "victim", "steps": 40, "height": 64, "width": 64})
        status, cres = post_json(
            base + f"/v1/requests/{second['id']}/cancel", {})
        assert status == 200 and cres["cancelled"] is True
        events = read_sse(base + second["events"])
        names = [n for n, _ in events]
        assert names[-1] == "cancelled" and names.count("cancelled") == 1
        # the first request is unaffected
        events1 = read_sse(base + first["events"])
        assert events1[-1][0] == "final"


def test_backpressure_drops_previews_never_stalls_scheduler():
    """No SSE consumer at all + a tiny event buffer: the request still
    completes at full speed, excess previews are dropped and counted."""
    cfg = serve_config(gateway=GatewayConfig(port=0, max_events=4))
    with InferenceServer(StepFakeExecutorFactory(batch_size=4),
                         cfg) as srv:
        base = srv.gateway_endpoint.url
        _, sub = post_json(base + "/v1/generate", {
            "prompt": "burst", "steps": 32, "height": 64, "width": 64})
        with urllib.request.urlopen(base + sub["poll"], timeout=5) as r:
            json.loads(r.read())
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            with urllib.request.urlopen(base + sub["poll"],
                                        timeout=5) as r:
                st = json.loads(r.read())
            if st["status"] != "pending":
                break
            time.sleep(0.02)
        assert st["status"] == "completed"
        assert st["dropped_previews"] > 0
        # the terminal event survived the drops: a late stream attach
        # still sees it
        events = read_sse(base + sub["events"])
        assert events[-1][0] == "final"
        drops = srv.registry.snapshot()["gateway_preview_drops"][0]["data"]
        assert sum(drops.values()) == st["dropped_previews"]


def test_gateway_over_fleet_failover():
    """Fleet-fronted gateway: a terminal failure on the first replica
    fails over and the HTTP client still gets its final image."""
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                p=1.0, max_fires=1)], seed=0)
    cfg = ServeConfig(
        max_queue_depth=32, batch_window_s=0.0, buckets=((64, 64),),
        warmup_buckets=(), default_steps=4,
        resilience=ResilienceConfig(max_retries=0))
    registry = MetricsRegistry()
    ledger = ExecutionLedger()
    reps = [
        Replica("heavy",
                LedgerFakeExecutorFactory(ledger, replica="heavy",
                                          batch_size=4),
                cfg, capacity_weight=10.0, fault_plan=plan,
                registry=registry),
        Replica("light",
                LedgerFakeExecutorFactory(ledger, replica="light",
                                          batch_size=4),
                cfg, capacity_weight=1.0, registry=registry),
    ]
    fleet = FleetRouter(reps, FleetConfig(tick_s=0), registry=registry)
    with fleet:
        gw = Gateway(fleet, config=GatewayConfig(port=0)).start(port=0)
        try:
            _, sub = post_json(gw.url + "/v1/generate", {
                "prompt": "only", "seed": 7, "height": 64, "width": 64,
                "steps": 4})
            events = read_sse(gw.url + sub["events"])
            assert events[-1][0] == "final"
            assert events[-1][1]["metrics"]["replica"] == "light"
            assert ledger.count("only", 7) == 1   # exactly once
        finally:
            gw.stop()
    snap = fleet.metrics_snapshot()["fleet"]
    assert snap["requests"]["failovers"] == 1


def test_http_stop_closes_open_streams():
    """server.stop() with a live SSE consumer attached: the stream ends
    (socket closes) instead of hanging past the drain."""
    cfg = serve_config(
        step_batching=StepBatchConfig(enabled=True, slots=1,
                                      preview_interval=1))
    factory = StepFakeExecutorFactory(batch_size=1, step_time_s=0.005)
    srv = InferenceServer(factory, cfg)
    srv.start()
    base = srv.gateway_endpoint.url
    _, sub = post_json(base + "/v1/generate", {
        "prompt": "long", "steps": 200, "height": 64, "width": 64})
    got = {}

    def consume():
        try:
            got["events"] = read_sse(base + sub["events"], timeout=30)
        except Exception as exc:  # noqa: BLE001 — abrupt close is fine
            got["error"] = exc

    t = sync.Thread(target=consume)
    t.start()
    time.sleep(0.2)   # consumer is mid-stream
    srv.stop()
    t.join(timeout=10)
    assert not t.is_alive()   # the stream resolved, one way or another


# --------------------------------------------------------------------------
# shared HTTP host (serve/httpbase.py)
# --------------------------------------------------------------------------


def test_httpbase_immediate_rebind():
    """The SO_REUSEADDR fix: a freshly stopped port rebinds immediately
    (previously TIME_WAIT made fast restarts flaky)."""
    import http.server

    class Ping(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b"pong"
            self.send_response(200)
            self.send_header("Content-Length", "4")
            self.end_headers()
            self.wfile.write(body)

    host = HTTPServerHost(Ping, port=0).start()
    port = host.port
    with urllib.request.urlopen(host.url + "/", timeout=5) as r:
        assert r.read() == b"pong"
    host.stop()
    # same fixed port, immediately
    host2 = HTTPServerHost(Ping, port=port).start()
    assert host2.port == port
    with urllib.request.urlopen(host2.url + "/", timeout=5) as r:
        assert r.read() == b"pong"
    host2.stop()


def test_metrics_endpoint_still_serves_after_refactor():
    """MetricsHTTPEndpoint rides HTTPServerHost now; its public contract
    (start/stop/url, /metrics + /healthz) is unchanged."""
    cfg = serve_config()
    with InferenceServer(StepFakeExecutorFactory(batch_size=4),
                         cfg) as srv:
        ep = srv.start_metrics_endpoint(port=0)
        with urllib.request.urlopen(ep.url + "/metrics", timeout=5) as r:
            assert b"serve_" in r.read()
        with urllib.request.urlopen(ep.url + "/healthz", timeout=5) as r:
            assert json.loads(r.read())["scheduler_alive"]

"""Native CLIP BPE tokenizer: id-level parity with transformers.

The reference tokenizes via the HF tokenizer stack (diffusers
from_pretrained); our native engine (native/clip_bpe.cc + native/bpe.py)
reads the same snapshot vocab.json/merges.txt.  The oracle is
`CLIPTokenizerFast` — the tokenizer diffusers actually instantiates — built
from the SAME fabricated vocab files, so every layer is compared: regex
pre-tokenization, byte->unicode mapping, merge order, framing, padding,
truncation.
"""

import json

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from distrifuser_tpu.native.bpe import NativeCLIPTokenizer, _bytes_to_unicode


@pytest.fixture(scope="module")
def tok_dir(tmp_path_factory):
    """A small but fully real CLIP-format vocab: all 256 byte symbols, their
    </w> variants, a handful of ranked merges, and the special tokens."""
    d = tmp_path_factory.mktemp("tokenizer")
    chars = list(_bytes_to_unicode().values())
    vocab = {}
    for c in chars:
        vocab[c] = len(vocab)
    for c in chars:
        vocab[c + "</w>"] = len(vocab)
    merges = [
        ("t", "h"),
        ("th", "e</w>"),
        ("a", "n"),
        ("an", "d</w>"),
        ("i", "n</w>"),
        ("c", "o"),
        ("co", "l"),
        ("o", "r</w>"),
        ("'", "s</w>"),
    ]
    for l, r in merges:
        vocab[l + r] = len(vocab)
    vocab["<|startoftext|>"] = len(vocab)
    vocab["<|endoftext|>"] = len(vocab)

    (d / "vocab.json").write_text(json.dumps(vocab), encoding="utf-8")
    (d / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(f"{l} {r}" for l, r in merges) + "\n",
        encoding="utf-8",
    )
    return str(d)


PROMPTS = [
    "Astronaut in a jungle, cold color palette, muted colors, detailed, 8k",
    "THE THEATER and the colors",
    "  multiple   spaces\tand\nnewlines  ",
    "it's the cat's color",
    "",
    "punctuation!!! (nested), [brackets]; #hash",
    "digits 123 456",
    "word " * 120,  # > 77 tokens: truncation framing must match
    "literal <|endoftext|> inside a prompt",  # added-token splitter parity
]


def test_native_matches_transformers_fast(tok_dir):
    ours = NativeCLIPTokenizer(tok_dir)
    theirs = transformers.CLIPTokenizerFast.from_pretrained(tok_dir)
    a = ours(PROMPTS, padding="max_length", max_length=77, truncation=True,
             return_tensors="np")["input_ids"]
    b = theirs(PROMPTS, padding="max_length", max_length=77, truncation=True,
               return_tensors="np")["input_ids"]
    np.testing.assert_array_equal(a, np.asarray(b))


def test_framing(tok_dir):
    tok = NativeCLIPTokenizer(tok_dir)
    ids = tok(["the"], max_length=77)["input_ids"][0]
    assert ids[0] == tok.bos_token_id
    assert ids[2] == tok.eos_token_id
    assert (ids[3:] == tok.eos_token_id).all()  # pad token is eos
    # 'the' merged fully: t+h -> th, th+e</w> -> the</w> = one id
    assert ids[1] != tok.bos_token_id and ids[1] != tok.eos_token_id


def test_merge_order_matters(tok_dir):
    """'color' hits ranked merges c+o -> co, co+l -> col; the remaining
    'o','r</w>' pair merges via o+r</w>.  Exercises the lowest-rank-first
    loop rather than left-to-right folding."""
    tok = NativeCLIPTokenizer(tok_dir)
    ids = tok.encode("color")
    with open(f"{tok_dir}/vocab.json", encoding="utf-8") as f:
        vocab = json.load(f)
    assert ids == [vocab["col"], vocab["or</w>"]]


def test_pipeline_prefers_native(tok_dir):
    from distrifuser_tpu.pipelines import _tokenizer_or_fallback

    tok = _tokenizer_or_fallback(tok_dir)
    assert isinstance(tok, NativeCLIPTokenizer)


def test_pad_token_from_special_tokens_map(tok_dir, tmp_path):
    """SDXL's tokenizer_2 declares pad_token '!' (id 0) — pad ids feed
    unmasked cross-attention, so the native tokenizer must honor the
    snapshot's declaration instead of assuming pad == eos."""
    import shutil

    d2 = tmp_path / "tokenizer_2"
    shutil.copytree(tok_dir, d2)
    (d2 / "special_tokens_map.json").write_text(
        json.dumps({"pad_token": "!",
                    "bos_token": "<|startoftext|>",
                    "eos_token": "<|endoftext|>"})
    )
    ours = NativeCLIPTokenizer(str(d2))
    with open(d2 / "vocab.json", encoding="utf-8") as f:
        vocab = json.load(f)
    assert ours.pad_token_id == vocab["!"]
    theirs = transformers.CLIPTokenizerFast.from_pretrained(str(d2))
    a = ours(PROMPTS, padding="max_length", max_length=77, truncation=True,
             return_tensors="np")["input_ids"]
    b = theirs(PROMPTS, padding="max_length", max_length=77, truncation=True,
               return_tensors="np")["input_ids"]
    np.testing.assert_array_equal(a, np.asarray(b))

"""MMDiT (SD3-class joint transformer) + flow-matching Euler scheduler.

The reference has no MMDiT/flow support (diffusers 0.24 predates SD3);
these pin the extension's own contracts: rectified-flow integration
exactness, joint-attention stream plumbing, and config rejection of
unsupported checkpoint families.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distrifuser_tpu.models import mmdit as mm
from distrifuser_tpu.schedulers import FlowMatchEulerScheduler, get_scheduler


def test_flow_euler_exact_on_straight_path():
    """With the optimal rectified-flow velocity v = noise - x0 (constant
    along the path), Euler integration from sigma=1 to 0 is EXACT for any
    step count: starting at pure noise the sampler must return x0 to
    float32 round-off, independent of shift."""
    rng = np.random.RandomState(0)
    x0 = jnp.asarray(rng.randn(2, 8, 8, 4), jnp.float32)
    noise = jnp.asarray(rng.randn(2, 8, 8, 4), jnp.float32)
    for n, shift in [(3, 3.0), (7, 3.0), (5, 1.0)]:
        sched = FlowMatchEulerScheduler(shift=shift).set_timesteps(n)
        x = noise * sched.init_noise_sigma
        state = sched.init_state(x.shape)
        for i in range(n):
            v = noise - x0
            x, state = sched.step(x, v, i, state)
        np.testing.assert_allclose(np.asarray(x), np.asarray(x0),
                                   atol=1e-5, rtol=0)


def test_flow_euler_tables_and_add_noise():
    sched = get_scheduler("flow-euler").set_timesteps(4)
    sig = np.asarray(sched._sigmas)
    assert sig[0] == pytest.approx(1.0)      # shift(1) == 1 for any shift
    assert sig[-1] == 0.0
    assert (np.diff(sig) < 0).all()          # strictly decreasing
    # shifted grid: s' = 3s/(1+2s) at the linspace points
    lin = np.linspace(1.0, 0.25, 4)
    np.testing.assert_allclose(sig[:-1], 3 * lin / (1 + 2 * lin), atol=1e-7)
    # model-facing timesteps are sigma * 1000
    np.testing.assert_allclose(np.asarray(sched.timesteps()), sig[:-1] * 1000,
                               atol=1e-4)
    # add_noise at step 0 is pure noise; prediction_type is pinned to flow
    x0 = jnp.ones((1, 4, 4, 2))
    noise = jnp.full((1, 4, 4, 2), 2.0)
    np.testing.assert_allclose(
        np.asarray(sched.add_noise(x0, noise, 0)), 2.0, atol=1e-6
    )
    assert sched.prediction_type == "flow"
    assert sched.init_noise_sigma == 1.0
    assert sched.scale_model_input(x0, 0) is x0


def test_mmdit_forward_shape_and_determinism():
    cfg = mm.tiny_mmdit_config()
    params = mm.init_mmdit_params(jax.random.PRNGKey(0), cfg)
    k = jax.random.PRNGKey(1)
    x = jax.random.normal(k, (2, cfg.sample_size, cfg.sample_size,
                              cfg.in_channels))
    enc = jax.random.normal(jax.random.fold_in(k, 1),
                            (2, 7, cfg.joint_attention_dim))
    pooled = jax.random.normal(jax.random.fold_in(k, 2),
                               (2, cfg.pooled_projection_dim))
    out = mm.mmdit_forward(params, cfg, x, jnp.asarray(500.0), enc, pooled)
    assert out.shape == (2, cfg.sample_size, cfg.sample_size,
                         cfg.out_channels)
    assert np.isfinite(np.asarray(out)).all()
    out2 = mm.mmdit_forward(params, cfg, x, jnp.asarray(500.0), enc, pooled)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # conditioning actually conditions: different t and different pooled
    # both change the output
    out_t = mm.mmdit_forward(params, cfg, x, jnp.asarray(100.0), enc, pooled)
    assert np.abs(np.asarray(out_t) - np.asarray(out)).max() > 0
    out_p = mm.mmdit_forward(params, cfg, x, jnp.asarray(500.0), enc,
                             pooled + 1.0)
    assert np.abs(np.asarray(out_p) - np.asarray(out)).max() > 0


def test_mmdit_block_kv_assemble_identity():
    """The displaced-patch hook with an identity assembly is bit-identical
    to the dense block — the runner's sync phase rides this contract."""
    cfg = mm.tiny_mmdit_config(depth=1)
    params = mm.init_mmdit_params(jax.random.PRNGKey(3), cfg)
    bp = jax.tree.map(lambda l: l[0], params["blocks"])
    k = jax.random.PRNGKey(4)
    x = jax.random.normal(k, (1, cfg.num_tokens, cfg.hidden_size))
    ctx = jax.random.normal(jax.random.fold_in(k, 1),
                            (1, 5, cfg.hidden_size))
    vec = jax.random.normal(jax.random.fold_in(k, 2), (1, cfg.hidden_size))
    a_x, a_c, (ak, av) = mm.mmdit_block(bp, cfg, x, ctx, vec)
    b_x, b_c, (bk, bv) = mm.mmdit_block(bp, cfg, x, ctx, vec,
                                        kv_assemble=lambda k_, v_: (k_, v_))
    np.testing.assert_array_equal(np.asarray(a_x), np.asarray(b_x))
    np.testing.assert_array_equal(np.asarray(a_c), np.asarray(b_c))
    np.testing.assert_array_equal(np.asarray(ak), np.asarray(bk))


def test_mmdit_config_rejections():
    # rms_norm qk-norm is SUPPORTED (test_qk_norm_config_from_json);
    # anything else is not
    with pytest.raises(ValueError, match="qk_norm"):
        mm.mmdit_config_from_json({"qk_norm": "rms_norm_across_heads"})
    # contiguous-prefix dual layouts are SUPPORTED (test_mmdit_dual);
    # anything else is not
    with pytest.raises(ValueError, match="contiguous-prefix"):
        mm.mmdit_config_from_json({"dual_attention_layers": [0, 2]})
    with pytest.raises(ValueError, match="pos_embed_max_size"):
        mm.MMDiTConfig(sample_size=512, patch_size=2, pos_embed_max_size=64)
    cfg = mm.mmdit_config_from_json(
        {"num_layers": 2, "num_attention_heads": 4, "attention_head_dim": 8,
         "sample_size": 32}
    )
    assert cfg.hidden_size == 32 and cfg.depth == 2


def test_mmdit_flow_generation_smoke():
    """End-to-end host-loop denoise with the flow sampler: finite, and the
    sampler actually moves the latent."""
    cfg = mm.tiny_mmdit_config(depth=2)
    params = mm.init_mmdit_params(jax.random.PRNGKey(5), cfg)
    sched = get_scheduler("flow-euler").set_timesteps(3)
    k = jax.random.PRNGKey(6)
    noise = jax.random.normal(
        k, (1, cfg.sample_size, cfg.sample_size, cfg.in_channels)
    )
    enc = jax.random.normal(jax.random.fold_in(k, 1),
                            (1, 7, cfg.joint_attention_dim))
    pooled = jax.random.normal(jax.random.fold_in(k, 2),
                               (1, cfg.pooled_projection_dim))
    x = noise * sched.init_noise_sigma
    state = sched.init_state(x.shape)
    fwd = jax.jit(lambda x, t: mm.mmdit_forward(params, cfg, x, t, enc,
                                                pooled))
    for i in range(3):
        v = fwd(x, sched.timesteps()[i])
        x, state = sched.step(x, v, i, state)
    arr = np.asarray(x)
    assert np.isfinite(arr).all()
    assert np.abs(arr - np.asarray(noise)).max() > 0


def test_qk_norm_forward_and_math():
    """SD3.5 qk_norm: per-head RMS with learned weights, fp32 moments —
    pinned against a manual oracle; the gated config runs end-to-end."""
    cfg = mm.tiny_mmdit_config(depth=2)
    import dataclasses

    cfg = dataclasses.replace(cfg, qk_norm=True)
    params = mm.init_mmdit_params(jax.random.PRNGKey(0), cfg)
    blk0 = jax.tree.map(lambda l: l[0], params["blocks"])
    assert blk0["x_qnorm"].shape == (cfg.hidden_size // cfg.num_heads,)

    # math oracle on one tensor
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, 3, cfg.hidden_size), jnp.float32)
    w = jnp.asarray(rng.rand(cfg.hidden_size // cfg.num_heads) + 0.5,
                    jnp.float32)
    got = np.asarray(mm._rms_heads(x, w, cfg.num_heads))
    xh = np.asarray(x).reshape(1, 3, cfg.num_heads, -1)
    ref = xh / np.sqrt((xh ** 2).mean(-1, keepdims=True) + 1e-6) * np.asarray(w)
    np.testing.assert_allclose(got, ref.reshape(1, 3, -1), rtol=1e-5,
                               atol=1e-5)

    k = jax.random.PRNGKey(1)
    out = mm.mmdit_forward(
        params, cfg,
        jax.random.normal(k, (1, cfg.sample_size, cfg.sample_size,
                              cfg.in_channels)),
        jnp.asarray(400.0),
        jax.random.normal(jax.random.fold_in(k, 1),
                          (1, 5, cfg.joint_attention_dim)),
        jax.random.normal(jax.random.fold_in(k, 2),
                          (1, cfg.pooled_projection_dim)),
    )
    assert np.isfinite(np.asarray(out)).all()
    # and the norm actually engages: zeroing the weights changes the output
    p2 = jax.tree.map(lambda l: l, params)
    p2["blocks"] = dict(params["blocks"])
    p2["blocks"]["x_qnorm"] = jnp.zeros_like(params["blocks"]["x_qnorm"])
    out2 = mm.mmdit_forward(
        p2, cfg,
        jax.random.normal(k, (1, cfg.sample_size, cfg.sample_size,
                              cfg.in_channels)),
        jnp.asarray(400.0),
        jax.random.normal(jax.random.fold_in(k, 1),
                          (1, 5, cfg.joint_attention_dim)),
        jax.random.normal(jax.random.fold_in(k, 2),
                          (1, cfg.pooled_projection_dim)),
    )
    assert np.abs(np.asarray(out2) - np.asarray(out)).max() > 0


def test_qk_norm_config_from_json():
    cfg = mm.mmdit_config_from_json(
        {"num_layers": 2, "num_attention_heads": 4, "attention_head_dim": 8,
         "sample_size": 32, "qk_norm": "rms_norm"}
    )
    assert cfg.qk_norm
    with pytest.raises(ValueError, match="rms_norm"):
        mm.mmdit_config_from_json({"qk_norm": "layer_norm"})

"""Per-op torch parity: converted weights + JAX ops vs torch modules.

The HF->JAX converter (models/weights.py) transposes every kernel; a wrong
axis order produces images that are garbage yet shape-correct, so random-
weight smoke tests cannot catch it.  These tests drive *diffusers-named*
torch state_dicts through the real converter (`_convert` / `_fuse_kv`) and
assert the JAX ops reproduce the torch ops bit-for-bit (fp32 tolerances) —
the single-device ground truth the reference inherits from torch
(/root/reference/distrifuser/modules/pp/conv2d.py, attn.py compute with
F.conv2d / F.scaled_dot_product_attention on the same weights).
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

from distrifuser_tpu.models.unet import layer_norm
from distrifuser_tpu.models.weights import _convert, _fuse_kv
from distrifuser_tpu.ops.attention import attention, sdpa
from distrifuser_tpu.ops.conv import conv2d
from distrifuser_tpu.ops.linear import feed_forward, linear
from distrifuser_tpu.ops.normalization import group_norm

RTOL, ATOL = 1e-4, 1e-5


def _sd(module, prefix):
    return {f"{prefix}.{k}": v.detach().numpy() for k, v in module.state_dict().items()}


def _assert_close(jax_out, torch_out):
    np.testing.assert_allclose(
        np.asarray(jax_out), torch_out.detach().numpy(), rtol=RTOL, atol=ATOL
    )


@pytest.mark.parametrize("k,stride,cin,cout", [(3, 1, 8, 16), (3, 2, 8, 16), (1, 1, 8, 4)])
def test_conv2d_parity(k, stride, cin, cout):
    torch.manual_seed(0)
    m = torch.nn.Conv2d(cin, cout, k, stride=stride, padding=(k - 1) // 2)
    p = _convert(_sd(m, "conv"))["conv"]
    x = torch.randn(2, cin, 12, 16)
    y_t = m(x)  # NCHW
    y_j = conv2d(p, np.asarray(x.permute(0, 2, 3, 1)), stride=stride)
    _assert_close(np.moveaxis(np.asarray(y_j), 3, 1), y_t)


def test_linear_parity():
    torch.manual_seed(1)
    m = torch.nn.Linear(24, 40)
    p = _convert(_sd(m, "lin"))["lin"]
    x = torch.randn(3, 7, 24)
    _assert_close(linear(p, np.asarray(x)), m(x))


def test_group_norm_parity():
    torch.manual_seed(2)
    m = torch.nn.GroupNorm(8, 32)
    with torch.no_grad():  # non-trivial affine
        m.weight.mul_(torch.randn(32) * 0.2 + 1.0)
        m.bias.add_(torch.randn(32) * 0.3)
    p = _convert(_sd(m, "gn"))["gn"]
    x = torch.randn(2, 32, 6, 10)
    y_j = group_norm(p, np.asarray(x.permute(0, 2, 3, 1)), groups=8)
    _assert_close(np.moveaxis(np.asarray(y_j), 3, 1), m(x))


def test_layer_norm_parity():
    torch.manual_seed(3)
    m = torch.nn.LayerNorm(48)
    with torch.no_grad():
        m.weight.mul_(torch.randn(48) * 0.2 + 1.0)
        m.bias.add_(torch.randn(48) * 0.3)
    p = _convert(_sd(m, "ln"))["ln"]
    x = torch.randn(2, 9, 48)
    _assert_close(layer_norm(p, np.asarray(x)), m(x))


@pytest.mark.parametrize("heads,lq,lk", [(4, 33, 33), (8, 16, 77)])
def test_sdpa_parity(heads, lq, lk):
    torch.manual_seed(4)
    b, d = 2, 16
    c = heads * d
    q = torch.randn(b, lq, c)
    kk = torch.randn(b, lk, c)
    v = torch.randn(b, lk, c)

    def split(t, l):  # [B, L, C] -> [B, H, L, D], torch head convention
        return t.view(b, l, heads, d).transpose(1, 2)

    y_t = (
        F.scaled_dot_product_attention(split(q, lq), split(kk, lk), split(v, lk))
        .transpose(1, 2)
        .reshape(b, lq, c)
    )
    y_j = sdpa(np.asarray(q), np.asarray(kk), np.asarray(v), heads=heads)
    _assert_close(y_j, y_t)


@pytest.mark.parametrize("cross", [False, True])
def test_attention_block_parity_fused_kv(cross):
    """Full attention block through the converter, incl. the to_k/to_v ->
    to_kv fusion (split_kv must un-interleave in the same order)."""
    torch.manual_seed(5)
    b, l, heads, d = 2, 24, 4, 8
    c = heads * d
    c_enc = 20 if cross else c
    to_q = torch.nn.Linear(c, c, bias=False)
    to_k = torch.nn.Linear(c_enc, c, bias=False)
    to_v = torch.nn.Linear(c_enc, c, bias=False)
    to_out = torch.nn.Linear(c, c)

    sd = {}
    for name, m in [("to_q", to_q), ("to_k", to_k), ("to_v", to_v)]:
        sd.update(_sd(m, f"attn.{name}"))
    sd.update(_sd(to_out, "attn.to_out.0"))  # diffusers ModuleList naming
    p = _fuse_kv(_convert(sd))["attn"]
    assert "to_kv" in p and "to_k" not in p

    x = torch.randn(b, l, c)
    enc = torch.randn(b, 11, c_enc) if cross else x

    def split(t):
        return t.view(b, -1, heads, d).transpose(1, 2)

    y_t = to_out(
        F.scaled_dot_product_attention(split(to_q(x)), split(to_k(enc)), split(to_v(enc)))
        .transpose(1, 2)
        .reshape(b, l, c)
    )
    y_j = attention(
        p, np.asarray(x), heads=heads,
        encoder_hidden_states=np.asarray(enc) if cross else None,
    )
    _assert_close(y_j, y_t)


def test_feed_forward_geglu_parity():
    """diffusers FeedForward(GEGLU): net.0.proj -> chunk -> a*gelu(g) -> net.2."""
    torch.manual_seed(6)
    c, inner = 16, 64
    proj = torch.nn.Linear(c, inner * 2)
    out = torch.nn.Linear(inner, c)
    sd = {**_sd(proj, "ff.net.0.proj"), **_sd(out, "ff.net.2")}
    p = _convert(sd)["ff"]
    assert "net_0" in p and "net_2" in p  # renamed, digit keys not listified

    x = torch.randn(2, 9, c)
    a, g = proj(x).chunk(2, dim=-1)
    y_t = out(a * F.gelu(g))
    _assert_close(feed_forward(p, np.asarray(x)), y_t)

"""PCPP partial refresh (DistriConfig.refresh_fraction): validation, the
strided take/scatter helpers, three-family stale parity at pinned
tolerances, warmup bit-exactness, stepwise==fused replay, byte-accurate
accounting (eval_shape only — no compiles for the acceptance mesh), the
closed-form comm_report/comm_plan keys, and the live StepTimeline
reconciliation at refresh_fraction < 1 (the PR-8 exact-reconciliation pin
extended to the partial-refresh byte model)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distrifuser_tpu.models.dit as dit_mod
import distrifuser_tpu.models.mmdit as mm
from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.parallel.compress import (
    refresh_period,
    scatter_every_kth,
    take_every_kth,
    validate_refresh_fraction,
)
from distrifuser_tpu.parallel.dit_sp import DiTDenoiseRunner
from distrifuser_tpu.parallel.mmdit_sp import MMDiTDenoiseRunner
from distrifuser_tpu.parallel.runner import DenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.utils.config import DistriConfig


# ---------------------------------------------------------------------------
# validation + helpers (no devices)
# ---------------------------------------------------------------------------


def test_refresh_fraction_validation():
    validate_refresh_fraction(1.0)
    validate_refresh_fraction(0.5)
    validate_refresh_fraction(0.25)
    assert refresh_period(0.5) == 2
    assert refresh_period(1.0) == 1
    for bad in (0.0, -0.5, 1.5, 0.3, 0.6):
        with pytest.raises(ValueError):
            validate_refresh_fraction(bad)

    kw = dict(devices=jax.devices()[:1], height=128, width=128)
    with pytest.raises(ValueError, match="refresh_fraction"):
        DistriConfig(refresh_fraction=0.3, **kw)
    with pytest.raises(ValueError, match="refresh traffic to thin"):
        DistriConfig(refresh_fraction=0.5, parallelism="tensor", **kw)
    with pytest.raises(ValueError, match="mutually exclusive"):
        DistriConfig(refresh_fraction=0.5, comm_batch=True, **kw)
    # pipefusion has no stale refresh to thin either
    with pytest.raises(ValueError, match="refresh traffic to thin"):
        DistriConfig(refresh_fraction=0.5, parallelism="pipefusion", **kw)


def test_dit_rejects_partial_refresh_off_gather():
    dcfg = dit_mod.tiny_dit_config()
    dparams = dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg)
    cfg = DistriConfig(devices=jax.devices()[:2],
                       height=dcfg.sample_size * 8,
                       width=dcfg.sample_size * 8, split_batch=False,
                       refresh_fraction=0.5, attn_impl="ring")
    with pytest.raises(ValueError, match="refresh collective to thin"):
        DiTDenoiseRunner(cfg, dcfg, dparams, get_scheduler("ddim"))


def test_take_scatter_helpers_roundtrip():
    x = jnp.arange(2 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 3)
    sub = take_every_kth(x, 2, jnp.asarray(1))
    np.testing.assert_array_equal(np.asarray(sub), np.asarray(x[:, 1::2]))
    back = scatter_every_kth(jnp.zeros_like(x), sub, 2, jnp.asarray(1))
    np.testing.assert_array_equal(np.asarray(back[:, 1::2]), np.asarray(sub))
    assert float(jnp.abs(back[:, 0::2]).sum()) == 0.0
    # grouped (tiled-all-gather layout): the stride applies within each
    # contiguous per-device segment
    xg = jnp.arange(2 * 12 * 3, dtype=jnp.float32).reshape(2, 12, 3)
    subg = take_every_kth(xg, 2, jnp.asarray(0), groups=2)
    exp = np.concatenate(
        [np.asarray(xg[:, 0:6:2]), np.asarray(xg[:, 6:12:2])], axis=1)
    np.testing.assert_array_equal(np.asarray(subg), exp)
    full = scatter_every_kth(xg, subg, 2, jnp.asarray(0), groups=2)
    np.testing.assert_array_equal(np.asarray(full), np.asarray(xg))
    with pytest.raises(ValueError, match="divisible"):
        take_every_kth(jnp.zeros((2, 7, 3)), 2, jnp.asarray(0))


# ---------------------------------------------------------------------------
# UNet family: parity / warmup exactness / stepwise replay (2-dev compiles)
# ---------------------------------------------------------------------------


def _unet_runner(n, **kw):
    kw.setdefault("warmup_steps", 1)
    kw.setdefault("split_batch", False)
    cfg = DistriConfig(devices=jax.devices()[:n], height=128, width=128,
                       parallelism="patch", **kw)
    ucfg = tiny_config(sdxl=False)
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    return DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim")), cfg, ucfg


def _unet_inputs(cfg, ucfg):
    k = jax.random.PRNGKey(42)
    lat = jax.random.normal(
        k, (1, cfg.latent_height, cfg.latent_width, ucfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 1, 7, ucfg.cross_attention_dim))
    return lat, enc


# Pinned partial-refresh parity tolerances (relative max vs the
# full-refresh run), measured on the tiny config at 2-dev sp2, 5 steps:
# f=0.5 1.18e-2 alone and with int8 / int8_residual stacked (the extra
# staleness dominates the quantization error).  ~4x margin for platform
# variation; far below the 0.35 displaced-mode gate in test_runner.py.
PCPP_UNET_TOL = 0.05


def test_unet_partial_refresh_parity_and_stepwise():
    r_off, cfg, ucfg = _unet_runner(2)
    lat, enc = _unet_inputs(cfg, ucfg)
    a = np.asarray(r_off.generate(lat, enc, num_inference_steps=5))
    r_half, _, _ = _unet_runner(2, refresh_fraction=0.5)
    b = np.asarray(r_half.generate(lat, enc, num_inference_steps=5))
    assert np.isfinite(b).all()
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    assert 0 < rel < PCPP_UNET_TOL, f"f=0.5 drift {rel}"
    # the host-driven stepwise loop replays the exact rotation schedule
    r_sw, _, _ = _unet_runner(2, refresh_fraction=0.5, use_cuda_graph=False)
    c = np.asarray(r_sw.generate(lat, enc, num_inference_steps=5))
    np.testing.assert_allclose(b, c, atol=2e-4)


@pytest.mark.slow  # secondary compiles: the fused-vs-stepwise pair above
# is the tier-1 gate; residual composition and warmup exactness add two
# more 2-dev fused programs to the 870s budget
def test_unet_partial_refresh_residual_and_warmup():
    r_off, cfg, ucfg = _unet_runner(2)
    lat, enc = _unet_inputs(cfg, ucfg)
    a = np.asarray(r_off.generate(lat, enc, num_inference_steps=5))
    # composition with the closed-loop residual coder stays bounded
    r_res, _, _ = _unet_runner(2, refresh_fraction=0.5,
                               comm_compress="int8_residual")
    d = np.asarray(r_res.generate(lat, enc, num_inference_steps=5))
    assert np.isfinite(d).all()
    rel = np.abs(a - d).max() / (np.abs(a).max() + 1e-6)
    assert 0 < rel < PCPP_UNET_TOL, f"f=0.5+residual drift {rel}"
    # a run that never leaves warmup is bit-identical: partial refresh
    # touches only the stale phase, sync exchanges always move whole
    r_w0, _, _ = _unet_runner(2, warmup_steps=4)
    r_w1, _, _ = _unet_runner(2, warmup_steps=4, refresh_fraction=0.5)
    w0 = np.asarray(r_w0.generate(lat, enc, num_inference_steps=3))
    w1 = np.asarray(r_w1.generate(lat, enc, num_inference_steps=3))
    np.testing.assert_array_equal(w0, w1)


# ---------------------------------------------------------------------------
# byte-accurate accounting (eval_shape only — the acceptance mesh runs in
# tier-1 without compiles)
# ---------------------------------------------------------------------------


def _bytes_report(devices8, **kw):
    cfg = DistriConfig(devices=devices8, height=128, width=128,
                       warmup_steps=1, parallelism="patch", **kw)
    ucfg = tiny_config(sdxl=False)
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    r = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    return r.comm_volume_report(per_phase=True)


def test_bytes_report_partial_refresh_reduction(devices8):
    """Acceptance: >= 1.5x stale-refresh BYTE reduction at fraction 0.5
    on the tiny config (the GN moments never thin, so the ratio lands
    between 1.5x and 2x), sync bytes identical, gn bytes identical."""
    off = _bytes_report(devices8)
    on = _bytes_report(devices8, refresh_fraction=0.5)
    assert off["bytes"]["sync"] == on["bytes"]["sync"]
    assert off["phases"] == on["phases"]  # carry shapes are fraction-blind
    s_off = sum(off["bytes"]["stale"].values())
    s_on = sum(on["bytes"]["stale"].values())
    assert s_off / s_on >= 1.5, (off["bytes"]["stale"], on["bytes"]["stale"])
    for kind in ("attn", "conv2d"):
        assert on["bytes"]["stale"][kind] < off["bytes"]["stale"][kind]
    assert on["bytes"]["stale"]["gn"] == off["bytes"]["stale"]["gn"]
    assert on["refresh_fraction"] == 0.5
    assert off["refresh_fraction"] == 1.0


def test_bytes_report_partial_composes_with_int8(devices8):
    """Fraction and quantization stack: int8 at fraction 0.5 spends less
    stale wire than either alone."""
    int8 = _bytes_report(devices8, comm_compress="int8")
    both = _bytes_report(devices8, comm_compress="int8",
                         refresh_fraction=0.5)
    half = _bytes_report(devices8, refresh_fraction=0.5)
    s = lambda rep: sum(rep["bytes"]["stale"].values())  # noqa: E731
    assert s(both) < s(int8)
    assert s(both) < s(half)


def test_dit_mmdit_closed_form_partial_keys():
    """The DiT/MMDiT closed forms carry the partial-refresh keys:
    full_refresh_* equals the fraction-1 report, the thinned per-step
    bytes shrink, sync stays whole."""
    dcfg = dit_mod.tiny_dit_config()
    dparams = dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg)

    def dit_rep(**kw):
        cfg = DistriConfig(devices=jax.devices()[:2],
                           height=dcfg.sample_size * 8,
                           width=dcfg.sample_size * 8, split_batch=False,
                           **kw)
        return DiTDenoiseRunner(cfg, dcfg, dparams,
                                get_scheduler("ddim")).comm_report()

    full, half = dit_rep(), dit_rep(refresh_fraction=0.5)
    assert half["refresh_fraction"] == 0.5
    assert (half["full_refresh_per_step_collective_bytes"]
            == full["per_step_collective_bytes"])
    assert (half["per_step_collective_bytes"]
            < full["per_step_collective_bytes"])
    assert (half["sync_step_collective_bytes"]
            == full["sync_step_collective_bytes"])

    mcfg = mm.tiny_mmdit_config()
    mparams = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)

    def mm_rep(**kw):
        cfg = DistriConfig(devices=jax.devices()[:2],
                           height=mcfg.sample_size * 8,
                           width=mcfg.sample_size * 8, split_batch=False,
                           **kw)
        return MMDiTDenoiseRunner(cfg, mcfg, mparams,
                                  get_scheduler("flow-euler")).comm_report()

    mfull, mhalf = mm_rep(), mm_rep(refresh_fraction=0.5)
    assert (mhalf["full_refresh_per_step_collective_bytes"]
            == mfull["per_step_collective_bytes"])
    assert (mhalf["per_step_collective_bytes"]
            < mfull["per_step_collective_bytes"])


# ---------------------------------------------------------------------------
# DiT / MMDiT numeric parity (2-dev compiles, 5 steps)
# ---------------------------------------------------------------------------

# Measured drifts on the tiny configs (2-dev, 5 steps): DiT 9.0e-5,
# MMDiT 8.0e-4 — an order below the compress-mode pins in
# test_compress.py.  ~10x margin.
PCPP_DIT_TOL = 5e-3
PCPP_MMDIT_TOL = 2e-2


def test_dit_partial_refresh_parity():
    dcfg = dit_mod.tiny_dit_config()
    params = dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg)
    k = jax.random.PRNGKey(3)
    lat = jax.random.normal(
        k, (1, dcfg.sample_size, dcfg.sample_size, dcfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 1, 8, dcfg.caption_dim))

    def mk(**kw):
        cfg = DistriConfig(devices=jax.devices()[:2],
                           height=dcfg.sample_size * 8,
                           width=dcfg.sample_size * 8, warmup_steps=1,
                           split_batch=False, **kw)
        return DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))

    a = np.asarray(mk().generate(lat, enc, num_inference_steps=5))
    b = np.asarray(mk(refresh_fraction=0.5).generate(
        lat, enc, num_inference_steps=5))
    assert np.isfinite(b).all()
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    assert 0 < rel < PCPP_DIT_TOL, f"DiT f=0.5 drift {rel}"


def test_mmdit_partial_refresh_parity():
    mcfg = mm.tiny_mmdit_config()
    params = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)
    k = jax.random.PRNGKey(7)
    lat = jax.random.normal(
        k, (1, mcfg.sample_size, mcfg.sample_size, mcfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 1, 5, mcfg.joint_attention_dim))
    pooled = jax.random.normal(
        jax.random.fold_in(k, 2), (2, 1, mcfg.pooled_projection_dim))

    def mk(**kw):
        cfg = DistriConfig(devices=jax.devices()[:2],
                           height=mcfg.sample_size * 8,
                           width=mcfg.sample_size * 8, warmup_steps=1,
                           split_batch=False, **kw)
        return MMDiTDenoiseRunner(cfg, mcfg, params,
                                  get_scheduler("flow-euler"))

    a = np.asarray(mk().generate(lat, enc, pooled, num_inference_steps=5))
    b = np.asarray(mk(refresh_fraction=0.5).generate(
        lat, enc, pooled, num_inference_steps=5))
    assert np.isfinite(b).all()
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    assert 0 < rel < PCPP_MMDIT_TOL, f"MMDiT f=0.5 drift {rel}"


# ---------------------------------------------------------------------------
# live StepTimeline <-> closed-form comm_plan reconciliation at f < 1
# (the PR-8 exact-reconciliation pin, extended to the PCPP byte model)
# ---------------------------------------------------------------------------


def test_comm_plan_partial_refresh_reconciles_live(devices8):
    from test_pipelines import build_sd_pipeline

    from distrifuser_tpu.utils.trace import StepTimeline

    pipe, _ = build_sd_pipeline(devices8, 2, split_batch=False,
                                refresh_fraction=0.5)
    tl = pipe.attach_step_timeline(StepTimeline())
    pipe("a cat", num_inference_steps=5, seed=0, output_type="latent")
    snap = tl.snapshot()
    plan = pipe.comm_plan(5)
    assert plan["refresh_fraction"] == 0.5
    # live per-executed-step byte counters == closed-form plan, exactly
    assert snap["comm_bytes"] == plan["total_bytes"]
    assert snap["comm_bytes_tracked"] is True
    # the half-refresh plan undercuts the full-refresh plan on the stale
    # phase by >= 1.5x (acceptance; GN moments never thin)
    pipe_full, _ = build_sd_pipeline(devices8, 2, split_batch=False)
    plan_full = pipe_full.comm_plan(5)
    assert (plan_full["bytes_per_step"]["sync"]
            == plan["bytes_per_step"]["sync"])
    ratio = (plan_full["bytes_per_step"]["stale"]
             / plan["bytes_per_step"]["stale"])
    assert ratio >= 1.5, ratio

"""Sequence-parallel VAE decode: exact parity with the dense decoder.

Unlike the UNet's displaced patch parallelism there is no staleness here —
fresh halo convs, pmean'd GroupNorm moments, exact ring mid attention — so
`decode_sp` must match `decode` to float tolerance, at every device count
that divides the rows, including through the q-chunked ring path.  The
reference decodes the full latent replicated on every rank
(/root/reference/distrifuser/pipelines.py:39-42); this is the beyond-
reference n-x-faster replacement, so exactness is the entire contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from distrifuser_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models import unet as unet_mod
from distrifuser_tpu.models import vae as vae_mod
from distrifuser_tpu.parallel.collectives import gather_rows


@pytest.fixture(scope="module")
def vae():
    cfg = vae_mod.tiny_vae_config()
    params = vae_mod.init_vae_params(jax.random.PRNGKey(0), cfg)
    lat = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 12, 4))
    return cfg, params, lat


@pytest.mark.parametrize("n", [2, 4, 8])
def test_decode_sp_matches_dense(vae, devices8, n):
    cfg, params, lat = vae
    dense = np.asarray(vae_mod.decode(params, cfg, lat))

    mesh = Mesh(np.array(devices8[:n]), axis_names=("sp",))
    out = shard_map(
        lambda p, l: gather_rows(vae_mod.decode_sp(p, cfg, l, n, axis="sp")),
        mesh=mesh, in_specs=(P(), P(None, "sp")), out_specs=P(),
        check_vma=False,
    )(params, lat)
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-4, atol=2e-4)


def test_decode_sp_chunked_ring_matches_dense(vae, devices8, monkeypatch):
    """Force the q-chunked ring (the 3840^2 memory-safety path) and require
    the same output."""
    cfg, params, lat = vae
    dense = np.asarray(vae_mod.decode(params, cfg, lat))
    monkeypatch.setattr(vae_mod, "_SP_CHUNK_LOGITS_ELEMS", 64)

    mesh = Mesh(np.array(devices8[:4]), axis_names=("sp",))
    out = shard_map(
        lambda p, l: gather_rows(vae_mod.decode_sp(p, cfg, l, 4, axis="sp")),
        mesh=mesh, in_specs=(P(), P(None, "sp")), out_specs=P(),
        check_vma=False,
    )(params, lat)
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("n", [2, 4])
def test_encode_sp_matches_dense(vae, devices8, n):
    """Encoder: one-sided downsample halo + shared sp helpers, exact."""
    cfg, params, _ = vae
    img = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 24, 3))
    dense = np.asarray(vae_mod.encode(params, cfg, img))

    mesh = Mesh(np.array(devices8[:n]), axis_names=("sp",))
    out = shard_map(
        lambda p, im: jax.lax.all_gather(
            vae_mod.encode_sp(p, cfg, im, n, axis="sp"), "sp", axis=1, tiled=True
        ),
        mesh=mesh, in_specs=(P(), P(None, "sp")), out_specs=P(),
        check_vma=False,
    )(params, img)
    np.testing.assert_allclose(np.asarray(out), dense, rtol=2e-4, atol=2e-4)


def test_pipeline_uses_sp_decode(devices8):
    """End-to-end: the same generation with vae_sp on and off must produce
    identical images (the decode is exact), and the sp path must actually be
    selected for a patch-parallel config."""
    from distrifuser_tpu.pipelines import DistriSDPipeline
    from distrifuser_tpu.schedulers import get_scheduler

    ucfg = unet_mod.tiny_config()
    uparams = unet_mod.init_unet_params(jax.random.PRNGKey(0), ucfg)
    vcfg = vae_mod.tiny_vae_config()
    vparams = vae_mod.init_vae_params(jax.random.PRNGKey(1), vcfg)
    from distrifuser_tpu.models import clip as clip_mod

    ccfg = clip_mod.tiny_clip_config()
    cparams = clip_mod.init_clip_params(jax.random.PRNGKey(2), ccfg)

    depth = len(ucfg.block_out_channels) - 1
    imgs = {}
    for vae_sp in (True, False):
        dcfg = DistriConfig(
            devices=devices8, height=8 * 8 * (1 << depth) * 2, width=128,
            warmup_steps=1, vae_sp=vae_sp,
        )
        pipe = DistriSDPipeline.from_params(
            dcfg, ucfg, uparams, vcfg, vparams, [ccfg], [cparams],
            scheduler=get_scheduler("ddim"),
        )
        # the parity check below is vacuous unless the branch really flips
        assert pipe.vae_decode_parallel == vae_sp
        out = pipe(prompt="a photo", num_inference_steps=2,
                   guidance_scale=5.0, seed=0, output_type="np")
        imgs[vae_sp] = np.asarray(out.images[0])
    np.testing.assert_allclose(imgs[True], imgs[False], rtol=1e-4, atol=1e-4)


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

"""Native metric implementations (utils/metrics.py): math-level validation.

Pretrained weights cannot exist on this box, so LPIPS/FID are validated at
the level the weights don't touch: metric identities (zero at identical
inputs, symmetry, positivity), the closed-form Fréchet distance between
known Gaussians, and the end-to-end directory flow with a random-weight
extractor.  Reference surface: scripts/compute_metrics.py (reference
computes the same three metrics, compute_metrics.py:62-79).
"""

import numpy as np
import pytest

from distrifuser_tpu.utils.metrics import (
    LPIPS,
    Counter,
    LatencyHistogram,
    feature_statistics,
    fid_from_features,
    frechet_distance,
    psnr,
)


def test_latency_histogram_quantiles_approximate():
    h = LatencyHistogram()
    r = np.random.RandomState(0)
    samples = np.abs(r.lognormal(mean=-2.0, sigma=1.0, size=5000))
    for s in samples:
        h.observe(float(s))
    assert h.count == 5000
    assert h.min == samples.min() and h.max == samples.max()
    assert h.mean == pytest.approx(samples.mean(), rel=1e-9)
    # bucket resolution is 2**0.25 per bucket -> ~19% relative error bound
    for q in (0.5, 0.9, 0.99):
        exact = float(np.quantile(samples, q))
        assert h.quantile(q) == pytest.approx(exact, rel=0.2), q


def test_latency_histogram_snapshot_and_empty():
    assert LatencyHistogram().snapshot() == {"count": 0}
    h = LatencyHistogram()
    h.observe(0.5)
    snap = h.snapshot()
    assert snap["count"] == 1
    # single observation: every quantile clamps to the exact value
    assert snap["p50"] == snap["p99"] == 0.5
    # out-of-range observations clamp to boundary buckets but keep exact
    # min/max/mean
    h2 = LatencyHistogram(lo=1e-3, hi=1.0)
    h2.observe(1e-6)
    h2.observe(50.0)
    assert h2.min == 1e-6 and h2.max == 50.0
    assert h2.quantile(0.0) >= 1e-6 and h2.quantile(1.0) <= 50.0


def test_counter():
    c = Counter()
    c.inc("a")
    c.inc("a", 2)
    c.inc("b")
    assert c.get("a") == 3 and c.get("missing") == 0
    assert c.snapshot() == {"a": 3, "b": 1}


def test_psnr_basics():
    r = np.random.RandomState(0)
    a = r.rand(16, 16, 3)
    assert psnr(a, a) >= 120.0  # mse floor -> 120 dB
    noisy = np.clip(a + 0.1 * r.randn(*a.shape), 0, 1)
    assert 10 < psnr(a, noisy) < 30
    # scaling the error down raises PSNR
    less_noisy = a + 0.5 * (noisy - a)
    assert psnr(a, less_noisy) > psnr(a, noisy)


def test_frechet_distance_closed_form():
    # identical Gaussians -> 0
    mu = np.array([1.0, -2.0])
    sig = np.array([[2.0, 0.3], [0.3, 1.0]])
    assert frechet_distance(mu, sig, mu, sig) == pytest.approx(0.0, abs=1e-8)
    # diagonal case: d = |mu1-mu2|^2 + sum((sqrt(s1)-sqrt(s2))^2)
    mu1, mu2 = np.array([0.0, 0.0]), np.array([3.0, 4.0])
    s1 = np.diag([4.0, 9.0])
    s2 = np.diag([1.0, 16.0])
    expect = 25.0 + (2 - 1) ** 2 + (3 - 4) ** 2
    assert frechet_distance(mu1, s1, mu2, s2) == pytest.approx(expect, rel=1e-9)


def test_fid_from_features_behaviour():
    r = np.random.RandomState(1)
    f0 = r.randn(500, 8)
    f1 = r.randn(500, 8)
    same_dist = fid_from_features(f0, f1)  # same distribution: near 0
    shifted = fid_from_features(f0, f1 + 3.0)  # mean shift of 3 in 8 dims
    assert same_dist < 1.0
    assert shifted == pytest.approx(8 * 9.0, rel=0.2)
    assert shifted > same_dist


def test_feature_statistics_shapes():
    f = np.random.RandomState(2).randn(10, 5)
    mu, sig = feature_statistics(f)
    assert mu.shape == (5,) and sig.shape == (5, 5)
    np.testing.assert_allclose(sig, sig.T)


def test_lpips_metric_identities():
    net = LPIPS.random(seed=0)
    r = np.random.RandomState(3)
    a = r.rand(64, 64, 3)
    b = r.rand(64, 64, 3)
    assert net(a, a) == pytest.approx(0.0, abs=1e-9)
    d_ab, d_ba = net(a, b), net(b, a)
    assert d_ab > 0
    assert d_ab == pytest.approx(d_ba, rel=1e-6)
    # a small perturbation scores closer than an unrelated image
    near = np.clip(a + 0.02 * r.randn(*a.shape), 0, 1)
    assert net(a, near) < d_ab


def test_lpips_rejects_incomplete_state():
    with pytest.raises(KeyError, match="missing"):
        LPIPS({"features.0.weight": np.zeros((64, 3, 11, 11), np.float32)})


def test_running_statistics_matches_batch():
    from distrifuser_tpu.utils.metrics import RunningStatistics

    r = np.random.RandomState(5)
    f = r.randn(100, 6)
    stats = RunningStatistics()
    for i in range(0, 100, 7):  # uneven batches
        stats.update(f[i : i + 7])
    mu_s, sig_s = stats.finalize()
    mu_b, sig_b = feature_statistics(f)
    np.testing.assert_allclose(mu_s, mu_b, rtol=1e-10)
    np.testing.assert_allclose(sig_s, sig_b, rtol=1e-8, atol=1e-12)


def test_fid_between_dirs_mixed_sizes(tmp_path):
    """Dirs with differing image sizes must stream without np.stack errors."""
    from PIL import Image

    from distrifuser_tpu.utils.metrics import fid_between_dirs

    r = np.random.RandomState(6)
    d0, d1 = tmp_path / "a", tmp_path / "b"
    d0.mkdir(), d1.mkdir()
    for i, size in enumerate([24, 32, 24, 32]):
        img = (r.rand(size, size, 3) * 255).astype(np.uint8)
        Image.fromarray(img).save(d0 / f"{i}.png")
        Image.fromarray(img).save(d1 / f"{i}.png")

    def extractor(imgs):  # size-insensitive features: channel means
        return imgs.reshape(len(imgs), -1, 3).mean(axis=1).astype(np.float64)

    assert fid_between_dirs(str(d0), str(d1), extractor, batch=3) == pytest.approx(
        0.0, abs=1e-9
    )


def test_fid_between_dirs_roundtrip(tmp_path):
    from PIL import Image

    from distrifuser_tpu.utils.metrics import fid_between_dirs

    r = np.random.RandomState(4)
    d0, d1 = tmp_path / "a", tmp_path / "b"
    d0.mkdir(), d1.mkdir()
    for i in range(6):
        img = (r.rand(32, 32, 3) * 255).astype(np.uint8)
        Image.fromarray(img).save(d0 / f"{i}.png")
        Image.fromarray(img).save(d1 / f"{i}.png")  # identical copies

    def extractor(imgs):  # random projection features
        rp = np.random.RandomState(0).randn(32 * 32 * 3, 4)
        return imgs.reshape(len(imgs), -1).astype(np.float64) @ rp

    assert fid_between_dirs(str(d0), str(d1), extractor) == pytest.approx(0.0, abs=1e-6)

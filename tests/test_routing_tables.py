"""Measured routing tables (sdpa + gemm) are reviewable DATA: they must
parse on import and carry provenance — the lint scripts/lint_route_tables.py
enforces in CI, run here under pytest so a local `pytest tests/` catches a
bad bake before the workflow does."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_route_tables_lint_clean():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import lint_route_tables
    finally:
        sys.path.pop(0)
    assert lint_route_tables.check_tables() == []


def test_lint_script_runs_as_tooling():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "lint_route_tables.py")],
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    assert "clean" in proc.stdout


def test_gemm_table_backend_declared_when_measured():
    from distrifuser_tpu.ops import gemm_routing

    if gemm_routing.MEASURED_ROUTES:
        assert gemm_routing.MEASURED_BACKEND in ("cpu", "tpu", "gpu")
    # provenance is never empty, measured or not
    assert gemm_routing.MEASURED_PROVENANCE.strip()

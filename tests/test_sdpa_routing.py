"""SDPA routing: env overrides > measured table > analytic default.

The reference always runs fused SDPA (modules/pp/attn.py:153); our backend
choice is a checked-in measured table (ops/sdpa_routing.py) with env vars
demoted to operator overrides. These tests pin the resolution order and the
log -> table updater round trip."""

import json
import os
import sys

import jax
import pytest

import importlib

attention = importlib.import_module("distrifuser_tpu.ops.attention")
from distrifuser_tpu.ops import sdpa_routing
from distrifuser_tpu.ops.sdpa_routing import Route

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))


class _Dev:
    def __init__(self, platform):
        self.platform = platform


@pytest.fixture(autouse=True)
def _clean_flash_env(monkeypatch):
    """Isolate routing tests from env leaked by other test files —
    __graft_entry__ setdefaults DISTRIFUSER_TPU_FLASH=0 process-wide when
    test_graft_entry runs earlier in the session.  Runs before each test
    body, so tests that set these vars intentionally still win."""
    for var in ("DISTRIFUSER_TPU_FLASH", "DISTRIFUSER_TPU_FLASH_IMPL",
                "DISTRIFUSER_TPU_FLASH_BQ", "DISTRIFUSER_TPU_FLASH_BK"):
        monkeypatch.delenv(var, raising=False)
    # the shipped model-validated override would shadow every monkeypatched
    # MEASURED_ROUTES below; tests that exercise overrides set their own
    monkeypatch.setattr(sdpa_routing, "MODEL_VALIDATED_OVERRIDES", {})


def _route(monkeypatch, platform="tpu", lq=4096, lk=4096, c=640, heads=10):
    import jax.numpy as jnp

    monkeypatch.setattr(jax, "devices", lambda: [_Dev(platform)])
    q = jax.ShapeDtypeStruct((2, lq, c), jnp.bfloat16)
    k = jax.ShapeDtypeStruct((2, lk, c), jnp.bfloat16)
    return attention._resolve_route(q, k, heads)


def test_env_off_wins_over_everything(monkeypatch):
    monkeypatch.setenv("DISTRIFUSER_TPU_FLASH", "0")
    monkeypatch.setattr(sdpa_routing, "MEASURED_ROUTES",
                        {(64, 12): Route("inrepo", 256, 512)})
    assert _route(monkeypatch) == Route("xla")


def test_unaligned_always_xla(monkeypatch):
    assert _route(monkeypatch, lq=4095, lk=4095) == Route("xla")


def test_cpu_defaults_to_xla(monkeypatch):
    assert _route(monkeypatch, platform="cpu") == Route("xla")


def test_force_on_cpu_is_inrepo_interpret_path(monkeypatch):
    monkeypatch.setenv("DISTRIFUSER_TPU_FLASH", "1")
    assert _route(monkeypatch, platform="cpu").impl == "inrepo"


def test_measured_table_drives_default_route(monkeypatch):
    monkeypatch.setattr(sdpa_routing, "MEASURED_ROUTES",
                        {(64, 12): Route("inrepo", 256, 512),
                         (64, 16): Route("xla")})
    # L=4096 -> bucket 12 -> measured inrepo with tuned tiles
    assert _route(monkeypatch) == Route("inrepo", 256, 512)
    # L=57600 -> bucket ~15.8 -> nearest measured is 16 -> xla beats flash
    assert _route(monkeypatch, lq=57600 // 8 * 8, lk=57344) == Route("xla")


def test_env_tiles_override_measured_tiles(monkeypatch):
    monkeypatch.setattr(sdpa_routing, "MEASURED_ROUTES",
                        {(64, 12): Route("inrepo", 256, 512)})
    monkeypatch.setenv("DISTRIFUSER_TPU_FLASH_BQ", "128")
    assert _route(monkeypatch) == Route("inrepo", 128, 512)


def test_explicit_impl_wins_over_table(monkeypatch):
    monkeypatch.setattr(sdpa_routing, "MEASURED_ROUTES",
                        {(64, 12): Route("xla")})
    monkeypatch.setenv("DISTRIFUSER_TPU_FLASH_IMPL", "upstream")
    assert _route(monkeypatch).impl == "upstream"


def test_unmeasured_falls_to_analytic_default(monkeypatch):
    monkeypatch.setattr(sdpa_routing, "MEASURED_ROUTES", {})
    assert _route(monkeypatch).impl == "upstream"  # long seq on TPU
    assert _route(monkeypatch, lq=512, lk=512).impl == "xla"  # short


def test_lookup_requires_matching_head_dim():
    # shipped table contents change with every campaign re-bake; pin only
    # the lookup semantics against a controlled table
    table = {(64, 12): Route("upstream")}
    old = sdpa_routing.MEASURED_ROUTES
    sdpa_routing.MEASURED_ROUTES = table
    try:
        assert sdpa_routing.lookup(5000, 64) == Route("upstream")
        assert sdpa_routing.lookup(5000, 160) is None
    finally:
        sdpa_routing.MEASURED_ROUTES = old


def test_lookup_distance_cap():
    """A lone long-L measurement must not govern short sequences (ADVICE
    r3): beyond MAX_BUCKET_DISTANCE log2 steps lookup falls through to the
    analytic default."""
    table = {(64, 14): Route("inrepo", 256, 512)}  # L=16384 only
    old = sdpa_routing.MEASURED_ROUTES
    sdpa_routing.MEASURED_ROUTES = table
    try:
        assert sdpa_routing.lookup(16384, 64) == Route("inrepo", 256, 512)
        assert sdpa_routing.lookup(8192, 64) is not None   # 1 step away
        assert sdpa_routing.lookup(1024, 64) is None       # 4 steps away
        assert sdpa_routing.lookup(2**20, 64) is None      # far the other way
    finally:
        sdpa_routing.MEASURED_ROUTES = old


def test_updater_tiles_keyed_by_head_dim(tmp_path):
    """Tuned tiles for one head_dim must not leak onto another head_dim's
    route at the same L (ADVICE r3)."""
    import json as _json

    import update_sdpa_table as upd

    log = tmp_path / "campaign.log"
    lines = [
        {"phase": "attn", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"xla": 2.0, "inrepo": 1.5}},
        {"phase": "attn", "L": 4096, "heads": 16, "head_dim": 72,
         "ms": {"xla": 2.2, "inrepo": 1.8}},
        {"phase": "tune", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"256x512": 1.2}},
        {"phase": "tune", "L": 4096, "heads": 16, "head_dim": 72,
         "ms": {"128x128": 1.6}},
    ]
    log.write_text("\n".join(_json.dumps(rec) for rec in lines) + "\n")
    attn, tune = upd.parse_log(str(log))
    routes = upd.build_routes(attn, tune)
    assert routes[(64, 12)][:3] == ("inrepo", 256, 512)
    assert routes[(72, 12)][:3] == ("inrepo", 128, 128)


def test_updater_upstream_tune_can_win(tmp_path):
    """A tuned upstream sweep that beats the default-tile attn comparison
    flips the route to upstream and carries its tiles."""
    import json as _json

    import update_sdpa_table as upd

    log = tmp_path / "campaign.log"
    lines = [
        {"phase": "attn", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"xla": 2.0, "inrepo": 1.5, "upstream": 1.8}},
        {"phase": "tune", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"256x512": 1.4}},
        {"phase": "tune_upstream", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"512x1024": 1.1, "256x512": 1.3}},
    ]
    log.write_text("\n".join(_json.dumps(rec) for rec in lines) + "\n")
    attn, tune = upd.parse_log(str(log))
    routes = upd.build_routes(attn, tune)
    assert routes[(64, 12)][:3] == ("upstream", 512, 1024)


def test_updater_round_trip(tmp_path):
    import update_sdpa_table as upd

    log = tmp_path / "campaign.log"
    lines = [
        {"phase": "attn", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"xla": 2.0, "inrepo": 1.5, "upstream": 1.0}},
        {"phase": "attn", "L": 16384, "heads": 10, "head_dim": 64,
         "ms": {"xla": 9.0, "inrepo": 8.0, "upstream": "failed:XlaError"}},
        # 7.5 ms sits just above the L=16384 roofline floor (~6.98 ms at
        # 100% bf16 peak) — the sanity guard must keep it
        {"phase": "tune", "L": 16384, "heads": 10, "head_dim": 64,
         "ms": {"128x128": 8.0, "256x512": 7.5}},
        {"phase": "b1024", "size": 1024, "s": 7.0},  # ignored: no ms dict
    ]
    log.write_text("non-json noise\n"
                   + "\n".join(json.dumps(rec) for rec in lines) + "\n")

    attn, tune = upd.parse_log(str(log))
    assert len(attn) == 2 and len(tune) == 1
    routes = upd.build_routes(attn, tune)
    assert routes[(64, 12)][0] == "upstream"
    impl, bq, bk, _comment = routes[(64, 14)]
    assert (impl, bq, bk) == ("inrepo", 256, 512)  # tuned tiles attached

    block = upd.render_block(routes, "unit-test")
    ns = {"Route": Route}
    exec(block.replace(upd.BEGIN, "").replace(upd.END, ""), ns)
    assert ns["MEASURED_ROUTES"][(64, 14)] == Route("inrepo", 256, 512)
    assert ns["MEASURED_PROVENANCE"] == "unit-test"


def test_updater_drops_subroofline_timings(tmp_path):
    """Campaign r5 regression: upstream-flash tune entries of ~0.02 ms at
    L=16384 (350x above bf16 peak — the kernel degenerates at those tiles
    instead of failing) must not reach the table; the sane sub-peak tiles
    of the same sweep still win."""
    import json as _json

    import update_sdpa_table as upd

    log = tmp_path / "campaign.log"
    lines = [
        {"phase": "attn", "L": 16384, "heads": 10, "head_dim": 64,
         "ms": {"xla": "failed:JaxRuntimeError", "inrepo": 184.9,
                "upstream": 161.8}},
        {"phase": "tune", "L": 16384, "heads": 10, "head_dim": 64,
         "ms": {"512x1024": 25.9}},
        {"phase": "tune_upstream", "L": 16384, "heads": 10, "head_dim": 64,
         "ms": {"256x2048": 23.2, "512x512": 0.022, "1024x512": 0.019}},
    ]
    log.write_text("\n".join(_json.dumps(rec) for rec in lines) + "\n")
    attn, tune = upd.parse_log(str(log))
    routes = upd.build_routes(attn, tune)
    impl, bq, bk, _comment = routes[(64, 14)]
    assert (impl, bq, bk) == ("upstream", 256, 2048)  # not the 0.02ms tiles
    # an attn record that is ENTIRELY sub-floor contributes nothing
    attn2 = [{"phase": "attn", "L": 16384, "heads": 10, "head_dim": 64,
              "ms": {"xla": 0.01, "upstream": 0.02}}]
    assert upd.build_routes(attn2, []) == {}


def test_updater_tiles_require_matching_head_count(tmp_path):
    """Campaign r5 regression: an h=10 tuned sweep must not fold into an
    h=24 attn record at the same (L, head_dim) — mixed-head comparison
    flipped the route to a kernel that loses at both head counts.  A
    heads-less record (pre-r5 logs) still matches any sweep (wildcard)."""
    import json as _json

    import update_sdpa_table as upd

    log = tmp_path / "campaign.log"
    lines = [
        # h=10 record first, h=24 record last (owns the route slot)
        {"phase": "attn", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"xla": 7.1, "inrepo": 13.8, "upstream": 12.2}},
        {"phase": "attn", "L": 4096, "heads": 24, "head_dim": 64,
         "ms": {"xla": 12.2, "inrepo": 29.4, "upstream": 26.3}},
        {"phase": "tune", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"512x1024": 8.2}},
    ]
    log.write_text("\n".join(_json.dumps(rec) for rec in lines) + "\n")
    attn, tune = upd.parse_log(str(log))
    routes = upd.build_routes(attn, tune)
    # the h=10 sweep (8.2ms) must NOT beat the h=24 record's xla (12.2ms)
    assert routes[(64, 12)][:3] == ("xla", None, None)

    # wildcard: heads-less attn record accepts the sweep
    lines2 = [
        {"phase": "attn", "L": 4096, "head_dim": 64,
         "ms": {"xla": 12.2, "inrepo": 13.8}},
        {"phase": "tune", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"512x1024": 8.2}},
    ]
    log.write_text("\n".join(_json.dumps(rec) for rec in lines2) + "\n")
    attn, tune = upd.parse_log(str(log))
    routes = upd.build_routes(attn, tune)
    assert routes[(64, 12)][:3] == ("inrepo", 512, 1024)


def test_model_validated_override_wins_and_scopes():
    """MODEL_VALIDATED_OVERRIDES outranks MEASURED_ROUTES at its bucket but
    obeys the same bucket-distance discipline elsewhere."""
    old_m = sdpa_routing.MEASURED_ROUTES
    old_o = sdpa_routing.MODEL_VALIDATED_OVERRIDES
    sdpa_routing.MEASURED_ROUTES = {(64, 12): Route("xla")}
    sdpa_routing.MODEL_VALIDATED_OVERRIDES = {
        (64, 12): Route("upstream", 256, 1024)}
    try:
        assert sdpa_routing.lookup(4096, 64) == Route("upstream", 256, 1024)
        # far buckets fall through the override to the measured table rules
        assert sdpa_routing.lookup(2**20, 64) is None
        # other head_dims see neither
        assert sdpa_routing.lookup(4096, 160) is None
        # a STRICTLY CLOSER measured entry beats the override: the override
        # is model-validated at ITS bucket only, not at lengths a nearer
        # measurement covers (L=1536 is 0.58 buckets from the (64,10) XLA
        # entry, 1.42 from the (64,12) override)
        sdpa_routing.MEASURED_ROUTES = {(64, 10): Route("xla"),
                                        (64, 12): Route("xla")}
        assert sdpa_routing.lookup(1536, 64) == Route("xla")
        assert sdpa_routing.lookup(4096, 64) == Route("upstream", 256, 1024)
    finally:
        sdpa_routing.MEASURED_ROUTES = old_m
        sdpa_routing.MODEL_VALIDATED_OVERRIDES = old_o


def test_updater_skips_tiles_slower_than_default(tmp_path):
    """A tuned sweep whose best time LOSES to the winner's default-tile
    time must not pin its tiles onto the route (the comment would claim a
    time those tiles never achieved)."""
    import json as _json

    import update_sdpa_table as upd

    log = tmp_path / "campaign.log"
    lines = [
        {"phase": "attn", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"xla": 9.0, "upstream": 7.0}},
        {"phase": "tune_upstream", "L": 4096, "heads": 10, "head_dim": 64,
         "ms": {"512x1024": 8.5}},  # tuned WORSE than default-tile 7.0
    ]
    log.write_text("\n".join(_json.dumps(rec) for rec in lines) + "\n")
    attn, tune = upd.parse_log(str(log))
    routes = upd.build_routes(attn, tune)
    assert routes[(64, 12)][:3] == ("upstream", None, None)


def test_sdpa_still_computes_on_cpu(monkeypatch):
    """End to end: routing lands on a working path whatever the table says."""
    import jax.numpy as jnp
    import numpy as np

    monkeypatch.setattr(sdpa_routing, "MEASURED_ROUTES",
                        {(64, 7): Route("inrepo", 64, 64)})
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (1, 128, 128), jnp.float32)
    out = attention.sdpa(q, q, q, heads=2)
    assert out.shape == (1, 128, 128)
    assert np.isfinite(np.asarray(out)).all()


def test_updater_accepts_bench_attention_lines(tmp_path):
    import update_sdpa_table as upd

    log = tmp_path / "bench_attention.log"
    lines = [
        {"impl": "xla", "L": 4096, "heads": 10, "ms": 2.0},
        {"impl": "pallas_inrepo", "L": 4096, "heads": 10, "ms": 1.4},
        {"impl": "pallas_upstream", "L": 4096, "heads": 10,
         "ms": "failed: XlaRuntimeError"},
    ]
    log.write_text("\n".join(json.dumps(rec) for rec in lines) + "\n")
    attn, tune = upd.parse_log(str(log))
    assert len(attn) == 1 and not tune
    routes = upd.build_routes(attn, tune)
    assert routes[(64, 12)][0] == "inrepo"  # failed upstream excluded


def test_updater_accepts_batch2_campaign_records(tmp_path):
    """chip_campaign.py emits ``batch=2`` (the CFG pair) in attn/tune
    records: the updater must carry them end to end — the roofline floor
    doubles (4*B*h*L^2*d flops), the batch lands in the table comment, and
    the rendered block round-trips."""
    import json as _json

    import update_sdpa_table as upd

    # b=2 floor at L=16384 h=10 d=64: 4*2*10*16384^2*64/197e12 ~= 6.98 ms
    floor_b2 = upd._roofline_floor_ms(
        {"L": 16384, "heads": 10, "head_dim": 64, "batch": 2})
    floor_b1 = upd._roofline_floor_ms(
        {"L": 16384, "heads": 10, "head_dim": 64})
    assert floor_b2 == pytest.approx(2 * floor_b1)

    log = tmp_path / "campaign.log"
    lines = [
        {"phase": "attn", "L": 16384, "heads": 10, "head_dim": 64,
         "batch": 2, "ms": {"xla": 30.0, "inrepo": 20.0, "upstream": 12.0}},
        # 5 ms sits ABOVE the b=1 floor (~3.5 ms) but BELOW the b=2 floor
        # (~6.98 ms): a b=2 record must drop it as a timing escape
        {"phase": "tune_upstream", "L": 16384, "heads": 10, "head_dim": 64,
         "batch": 2, "ms": {"512x512": 5.0, "256x1024": 10.0}},
    ]
    log.write_text("\n".join(_json.dumps(rec) for rec in lines) + "\n")
    attn, tune = upd.parse_log(str(log))
    assert attn[0]["batch"] == 2 and tune[0]["batch"] == 2
    routes = upd.build_routes(attn, tune)
    impl, bq, bk, comment = routes[(64, 14)]
    assert (impl, bq, bk) == ("upstream", 256, 1024)  # not the 5 ms escape
    assert "b=2" in comment
    block = upd.render_block(routes, "unit-test-b2")
    ns = {"Route": Route}
    exec(block.replace(upd.BEGIN, "").replace(upd.END, ""), ns)
    assert ns["MEASURED_ROUTES"][(64, 14)] == Route("upstream", 256, 1024)


def test_lookup_nearest_shape_fallback_for_missing_key():
    """The table is keyed by (head_dim, log2 L) — a query whose exact
    (batch, seq, heads) combination was never measured still routes via
    the NEAREST measured bucket at its head_dim (within
    MAX_BUCKET_DISTANCE), and falls through to the analytic default
    beyond it.  Batch and head count deliberately do not partition the
    table: the campaign measures the CFG pair at the model's head counts,
    and the latency ordering tracks sequence-length scale."""
    table = {(64, 12): Route("upstream", 256, 1024),
             (64, 14): Route("inrepo", 512, 512)}
    old = sdpa_routing.MEASURED_ROUTES
    sdpa_routing.MEASURED_ROUTES = table
    try:
        # L=6000 (bucket ~12.55) was never measured: nearest is 12
        assert sdpa_routing.lookup(6000, 64) == Route("upstream", 256, 1024)
        # L=11585 (bucket ~13.5): ties resolve to a measured neighbor,
        # never to None, as long as one is in range
        assert sdpa_routing.lookup(11585, 64) in table.values()
        # L=23000 (bucket ~14.5): nearest is 14
        assert sdpa_routing.lookup(23000, 64) == Route("inrepo", 512, 512)
        # missing head_dim: no fallback across head_dims
        assert sdpa_routing.lookup(6000, 128) is None
        # far outside every measured bucket: analytic default decides
        assert sdpa_routing.lookup(240, 64) is None
    finally:
        sdpa_routing.MEASURED_ROUTES = old


def test_largest_dividing_tile():
    """Tile fitting for the upstream kernel (ADVICE r4): a tuned tile that
    does not divide the call's length is halved to the largest power-of-2
    divisor instead of being dropped (which would mix in the kernel's
    hardcoded 512/1024 defaults — themselves non-dividing for shapes like
    Lk=57600)."""
    fit = attention._largest_dividing_tile
    assert fit(512, 4096) == 512          # already divides
    assert fit(1024, 57600) == 256        # 1024, 512 fail; 256 divides
    assert fit(512, 57600) == 256
    assert fit(1024, 77) is None          # below the 128 lane minimum
    assert fit(128, 384) == 128
    assert fit(1024, 1000) is None        # no pow2 >=128 divides 1000

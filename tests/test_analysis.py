"""distrilint framework: every checker fails on its seeded violation,
the baseline round-trips with provenance enforcement, fingerprints are
stable across unrelated edits, and the jaxpr overlap gate agrees with
the slow HLO tests' classification on the tiny config — fast enough to
run un-slow-marked on the 2-core tier-1 runner (trace, never compile).
"""

import ast
import dataclasses
import os
import subprocess
import sys
import textwrap

import pytest

from distrifuser_tpu.analysis import (
    Baseline,
    BaselineError,
    CheckContext,
    Finding,
    apply_baseline,
    render_baseline,
    run_checkers,
)
from distrifuser_tpu.analysis.checkers import (
    collective_containment,
    compile_identity,
    lock_discipline,
    overlap_gate,
    route_tables,
    typed_raises,
)
from distrifuser_tpu.analysis.checkers.lock_discipline import guard
from distrifuser_tpu.analysis.jaxpr_overlap import (
    analyze_jaxpr_collectives,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def real_ctx():
    return CheckContext(REPO)


# ---------------------------------------------------------------------------
# acceptance: the shipped tree is clean under the checked-in baseline


def test_shipped_tree_strict_clean():
    """`--strict` semantics in-process: zero non-baselined findings and
    zero stale baseline entries on the tree as shipped."""
    results = run_checkers(real_ctx())
    findings = [f for fs in results.values() for f in fs]
    baseline = Baseline.load(os.path.join(
        REPO, "distrifuser_tpu", "analysis", "baseline.txt"))
    applied = apply_baseline(findings, baseline)
    assert not applied.new, [f.render() for f in applied.new]
    assert not applied.stale, [e.fingerprint for e in applied.stale]
    # all seven checkers actually ran (a crashed checker emits findings)
    assert set(results) == {
        "typed-raises", "collective-containment", "sync-containment",
        "lock-discipline", "compile-identity", "route-tables",
        "jaxpr-overlap",
    }


def test_cli_runs_fast_checkers(tmp_path):
    """The module entry point works as a subprocess (the CI invocation
    shape), restricted to AST checkers so the test stays cheap."""
    out = tmp_path / "findings.json"
    proc = subprocess.run(
        [sys.executable, "-m", "distrifuser_tpu.analysis", "--strict",
         "--checker", "typed-raises", "--checker", "lock-discipline",
         "--json", str(out)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    assert out.exists()
    assert "distrilint ok" in proc.stdout


# ---------------------------------------------------------------------------
# compile-identity: removing any single wiring station fails the gate


def _model():
    return compile_identity.build_model(real_ctx())


def test_compile_identity_clean_on_real_tree():
    assert compile_identity.check_model(_model()) == []


@pytest.mark.parametrize("field", [
    f.name for f in __import__(
        "dataclasses").fields(__import__(
            "distrifuser_tpu.serve.cache",
            fromlist=["ExecKey"]).ExecKey)])
def test_removing_any_exec_key_field_fails(field):
    """ISSUE 13 acceptance: drop any single ExecKey field and the gate
    fails — via the ServeConfig mirror rule, a dangling short()/policy
    reference, or a dangling _exec_key_for kwarg."""
    m = _model()
    mutated = dataclasses.replace(
        m, exec_key_fields=tuple(f for f in m.exec_key_fields
                                 if f != field))
    findings = compile_identity.check_model(mutated)
    assert findings, f"removing ExecKey.{field} went undetected"


@pytest.mark.parametrize("station,attr_field", [
    ("short_attrs", "short"),
    ("policy_attrs", "policy"),
    ("key_call_kwargs", "key-for"),
])
def test_removing_handling_fails(station, attr_field):
    """Dropping a field's handling from short()/apply_key_policy/
    _exec_key_for (modelled by removing it from the extracted attr set)
    fails the gate for every non-allowlisted field."""
    m = _model()
    for field in m.exec_key_fields:
        if station == "policy_attrs" and (
                field in compile_identity.STRUCTURAL_FIELDS):
            continue
        if station == "key_call_kwargs" and (
                field in compile_identity.LADDER_ONLY_ALLOWLIST):
            continue
        attrs = frozenset(getattr(m, station) - {field})
        mutated = dataclasses.replace(m, **{station: attrs})
        findings = compile_identity.check_model(mutated)
        idents = {f.identity for f in findings}
        assert f"{attr_field}:{field}" in idents, (
            f"dropping {field} from {station} went undetected")


def test_unmirrored_serve_knob_fails():
    """The seeded violation the checker exists for: a new ServeConfig
    knob with no ExecKey field and no allowlist entry."""
    m = _model()
    mutated = dataclasses.replace(
        m, serve_config_fields=m.serve_config_fields + ("new_knob",))
    findings = compile_identity.check_model(mutated)
    assert any(f.identity == "mirror:new_knob" for f in findings)


def test_stale_allowlist_entry_fails(monkeypatch):
    monkeypatch.setitem(compile_identity.SERVE_RUNTIME_ALLOWLIST,
                        "ghost_knob", "no longer exists")
    findings = compile_identity.check_model(_model())
    assert any(f.identity == "allowlist-stale:ghost_knob"
               for f in findings)


# ---------------------------------------------------------------------------
# collective containment: seeded raw collective


RAW_COLLECTIVE_SRC = textwrap.dedent("""\
    from jax import lax

    def leak(x, axis):
        g = lax.all_gather(x, axis)
        return g.sum()

    def leak_twice(x, axis):
        a = lax.ppermute(x, axis, perm=[(0, 1)])
        b = lax.ppermute(a, axis, perm=[(1, 0)])
        return a + b
""")


def test_raw_collective_fixture_flagged():
    tree = ast.parse(RAW_COLLECTIVE_SRC)
    findings = collective_containment.scan_module(
        tree, "distrifuser_tpu/models/fixture.py")
    idents = {f.identity for f in findings}
    assert idents == {"leak:all_gather:0", "leak_twice:ppermute:0",
                      "leak_twice:ppermute:1"}


def test_blessed_module_not_flagged():
    tree = ast.parse(RAW_COLLECTIVE_SRC)
    assert collective_containment.scan_module(
        tree, "distrifuser_tpu/parallel/collectives.py") == []


def test_wrapper_calls_not_flagged():
    src = textwrap.dedent("""\
        from ..parallel.collectives import all_gather, psum

        def fine(x, axis):
            return psum(all_gather(x, axis), axis)
    """)
    assert collective_containment.scan_module(
        ast.parse(src), "distrifuser_tpu/models/fixture.py") == []


def test_unaliased_jax_lax_import_flagged():
    """`import jax.lax; jax.lax.psum(...)` must not evade the gate."""
    for imp in ("import jax.lax",
                "import jax.lax as L",
                "import jax"):
        base = {"import jax.lax": "jax.lax",
                "import jax.lax as L": "L",
                "import jax": "jax.lax"}[imp]
        src = f"{imp}\n\ndef leak(x, axis):\n    return {base}.psum(x, axis)\n"
        findings = collective_containment.scan_module(
            ast.parse(src), "distrifuser_tpu/models/fixture.py")
        assert [f.identity for f in findings] == ["leak:psum:0"], imp


def test_from_import_collective_flagged():
    src = textwrap.dedent("""\
        from jax.lax import all_gather as ag

        def leak(x, axis):
            return ag(x, axis)
    """)
    findings = collective_containment.scan_module(
        ast.parse(src), "distrifuser_tpu/ops/fixture.py")
    assert [f.identity for f in findings] == ["leak:all_gather:0"]


# ---------------------------------------------------------------------------
# lock discipline: seeded unguarded mutation


LOCK_FIXTURE_SRC = textwrap.dedent("""\
    class Cacheish:
        def __init__(self):
            self._entries = {}
            self._lock = object()
            self.hits = 0

        def good(self, k, v):
            with self._lock:
                self._entries[k] = v
                self.hits += 1

        def bad_assign(self, k, v):
            self._entries[k] = v

        def bad_augassign(self):
            self.hits += 1

        def bad_method(self, k):
            self._entries.pop(k, None)

        def _evict_locked(self, k):
            del self._entries[k]

        def bad_closure(self):
            with self._lock:
                def worker():
                    self.hits += 1
                return worker
""")


def _lock_findings(src=LOCK_FIXTURE_SRC):
    cls = ast.parse(src).body[0]
    spec = guard("_lock", ["_entries", "hits"])
    return lock_discipline.scan_class(cls, spec, "serve/fixture.py")


def test_lock_fixture_flags_unguarded_mutations():
    idents = {f.identity for f in _lock_findings()}
    assert idents == {
        "Cacheish.bad_assign:_entries:0",
        "Cacheish.bad_augassign:hits:0",
        "Cacheish.bad_method:_entries:0",
        # the closure runs on another thread: the enclosing with-block
        # does not protect it
        "Cacheish.worker:hits:0",
    }


def test_lock_registry_names_live_classes():
    findings = lock_discipline.run(real_ctx())
    assert not [f for f in findings
                if f.identity.startswith("registry-missing")], (
        [f.render() for f in findings])


# ---------------------------------------------------------------------------
# typed raises: seeded bare raise


def test_bare_raise_fixture_flagged():
    src = textwrap.dedent("""\
        class S:
            def hot(self):
                raise RuntimeError("boom")

            def validate(self, x):
                if x < 0:
                    raise ValueError("fine")

            def typed(self):
                raise ServerClosedError("fine")

        def reraise(exc):
            raise Exception
    """)
    findings = typed_raises.scan_module(
        ast.parse(src), "distrifuser_tpu/serve/fixture.py")
    assert {f.identity for f in findings} == {
        "S.hot:RuntimeError:0", "reraise:Exception:0"}


# ---------------------------------------------------------------------------
# route tables: seeded provenance violations (live-module monkeypatch)


def test_route_tables_clean_then_seeded(monkeypatch):
    assert route_tables.check_tables() == []
    from distrifuser_tpu.ops import sdpa_routing

    monkeypatch.setattr(sdpa_routing, "MEASURED_PROVENANCE", "")
    findings = route_tables.check_tables()
    assert any(f.identity == "sdpa:provenance-missing" for f in findings)


def test_route_tables_malformed_entry(monkeypatch):
    from distrifuser_tpu.ops import gemm_routing

    monkeypatch.setattr(
        gemm_routing, "MEASURED_ROUTES",
        {("int4", 5): next(iter(gemm_routing.MEASURED_ROUTES.values()))}
        if gemm_routing.MEASURED_ROUTES else
        {("int4", 5): gemm_routing.GemmRoute("dot")})
    findings = route_tables.check_tables()
    assert any(f.identity.startswith("gemm:key") for f in findings)


# ---------------------------------------------------------------------------
# baseline: round-trip, provenance enforcement, stale detection


def _finding(ident="f:x:0", path="a/b.py", checker="typed-raises"):
    return Finding(checker=checker, path=path, line=7,
                   message="seeded", identity=ident)


def test_baseline_round_trip():
    f1, f2 = _finding("one"), _finding("two")
    text = render_baseline([f1, f2])
    # machine-written entries carry the UNREVIEWED placeholder: parsing
    # must REJECT them until a human writes the reason
    with pytest.raises(BaselineError, match="UNREVIEWED"):
        Baseline.parse(text)
    text = text.replace(
        "UNREVIEWED — justify this suppression or fix the finding",
        "deliberate: seeded fixture")
    baseline = Baseline.parse(text)
    assert len(baseline.entries) == 2
    applied = apply_baseline([f1, f2], baseline)
    assert not applied.new and not applied.stale
    assert len(applied.suppressed) == 2
    # reasons survive a re-render (the add/expire cycle)
    again = Baseline.parse(render_baseline([f1, f2], previous=baseline))
    assert all(e.reason == "deliberate: seeded fixture"
               for e in again.entries)


def test_baseline_stale_entry_detected():
    f1, f2 = _finding("one"), _finding("two")
    text = render_baseline([f1, f2], previous=None).replace(
        "UNREVIEWED — justify this suppression or fix the finding", "ok")
    baseline = Baseline.parse(text)
    applied = apply_baseline([f1], baseline)  # f2 healed
    assert len(applied.stale) == 1
    assert applied.stale[0].fingerprint == f2.fingerprint


def test_baseline_requires_provenance():
    f = _finding("one")
    entry = f"{f.fingerprint} {f.checker} {f.path} seeded\n"
    with pytest.raises(BaselineError, match="provenance"):
        Baseline.parse(entry)
    # a blank line detaches a reason from a later entry
    with pytest.raises(BaselineError, match="provenance"):
        Baseline.parse(f"# provenance: ok\n\n{entry}")
    # attached reason parses
    assert len(Baseline.parse(
        f"# provenance: ok\n{entry}").entries) == 1


def test_baseline_rejects_malformed_lines():
    with pytest.raises(BaselineError, match="unparseable"):
        Baseline.parse("# provenance: ok\nnot-a-fingerprint\n")
    with pytest.raises(BaselineError, match="fingerprint"):
        Baseline.parse("# provenance: ok\nZZZZZZZZZZZZ c p note\n")


def test_shipped_baseline_parses_with_reasons():
    baseline = Baseline.load(os.path.join(
        REPO, "distrifuser_tpu", "analysis", "baseline.txt"))
    assert baseline.entries, "shipped baseline expected to be non-empty"
    assert all(e.reason for e in baseline.entries)


# ---------------------------------------------------------------------------
# fingerprints: stable across unrelated edits, distinct per violation


def test_fingerprint_stable_across_unrelated_edits():
    before = collective_containment.scan_module(
        ast.parse(RAW_COLLECTIVE_SRC), "distrifuser_tpu/x.py")
    shifted = ("# comment\n" * 40) + RAW_COLLECTIVE_SRC
    after = collective_containment.scan_module(
        ast.parse(shifted), "distrifuser_tpu/x.py")
    assert [f.fingerprint for f in before] == [
        f.fingerprint for f in after]
    assert [f.line for f in before] != [f.line for f in after]


def test_fingerprint_distinguishes_path_and_checker():
    a = _finding("one", path="a.py")
    b = _finding("one", path="b.py")
    c = _finding("one", path="a.py", checker="lock-discipline")
    assert len({a.fingerprint, b.fingerprint, c.fingerprint}) == 3


# ---------------------------------------------------------------------------
# jaxpr overlap: synthetic fixtures + agreement with the HLO tests


def _scan_reports(body_fn, n_carry_args, devices8):
    """Trace a shard_map'd scan over the 8-device mesh and analyze it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from distrifuser_tpu.utils.compat import shard_map

    mesh = Mesh(devices8, ("sp",))

    def device_fn(*carry):
        def body(c, _):
            return body_fn(*c), None

        out, _ = jax.lax.scan(body, carry, jnp.arange(4))
        return out

    fn = shard_map(device_fn, mesh=mesh,
                   in_specs=tuple(P("sp") for _ in range(n_carry_args)),
                   out_specs=tuple(P("sp") for _ in range(n_carry_args)))
    args = [jnp.ones((8, 4)) for _ in range(n_carry_args)]
    cj = jax.make_jaxpr(fn)(*args)
    return analyze_jaxpr_collectives(cj)


PERM = [(i, (i + 1) % 8) for i in range(8)]


def test_jaxpr_deferred_fixture(devices8):
    """Seeded deferred collective: ppermute straight to the carry."""
    import jax.numpy as jnp
    from jax import lax

    def body(x, stale):
        y = x * 1.5 + stale  # consume LAST step's exchange
        fresh = lax.ppermute(y, "sp", PERM)  # this step's: carry-only
        return y, fresh

    reports = _scan_reports(body, 2, devices8)
    (report,) = [r for r in reports if r.n_collectives]
    assert report.deferred and not report.inline, report
    assert list(report.deferred.values()) == ["ppermute"]
    del jnp  # silence linters


def test_jaxpr_inline_fixture(devices8):
    """Seeded inlined collective: the ppermute output feeds a matmul in
    the same iteration — must classify inline."""
    import jax.numpy as jnp
    from jax import lax

    def body(x, stale):
        g = lax.ppermute(x, "sp", PERM)
        y = x @ g.T + stale * 0.5  # same-step compute on the exchange
        return y, g

    reports = _scan_reports(body, 2, devices8)
    (report,) = [r for r in reports if r.n_collectives]
    assert report.inline and not report.deferred, report
    del jnp


def test_jaxpr_deferred_compute_fixture(devices8):
    """Elementwise-only consumers en route to the carry classify
    deferred_compute (the dequant-chain carve-out), never deferred."""
    from jax import lax

    def body(x, stale):
        y = x * 1.5 + stale
        fresh = lax.ppermute(y, "sp", PERM) * 0.25 + 1.0  # dequant-ish
        return y, fresh

    reports = _scan_reports(body, 2, devices8)
    (report,) = [r for r in reports if r.n_collectives]
    assert report.deferred_compute and not report.inline, report
    assert not report.deferred


def test_overlap_gate_fails_on_seeded_inline_report():
    """Seeded violation for the gate itself: a stale scan whose refresh
    ppermutes turned inline must produce findings (inline-count,
    inline-kind, halo-missing all fire)."""
    from distrifuser_tpu.analysis.jaxpr_overlap import JaxprLoopReport

    bad = JaxprLoopReport(
        kind="scan",
        deferred={f"all_gather#{i}": "all_gather" for i in range(12)},
        inline={"ppermute#0": "ppermute", "ppermute#1": "ppermute",
                "ppermute#2": "ppermute"},
        deferred_compute={},
    )
    findings = overlap_gate._gate_stale([bad], "stale")
    idents = {f.identity for f in findings}
    assert "stale:inline-count" in idents
    assert "stale:inline-kind" in idents
    assert "stale:halo-missing" in idents
    # and an empty program is itself a finding, never a silent pass
    assert overlap_gate._gate_stale([], "stale")[0].identity == (
        "stale:no-loops")


@pytest.fixture(scope="module")
def stale_reports(devices8):
    del devices8  # ensures the 8-device mesh exists before tracing
    return analyze_jaxpr_collectives(
        overlap_gate._trace_tiny("corrected_async_gn", 4))


def test_jaxpr_agrees_with_hlo_on_tiny_config(stale_reports):
    """The fast gate agrees with the slow HLO tests
    (tests/test_overlap.py) on the tiny config: every refresh collective
    of the stale scan is carry-only (halo ppermutes + KV gathers), and
    the only same-step consumers are the <=2 output/CFG gathers."""
    stale = max(stale_reports,
                key=lambda r: r.n_deferred + r.n_deferred_compute)
    hidden = {**stale.deferred, **stale.deferred_compute}
    assert stale.n_inline <= 2, stale.inline
    assert all(p == "all_gather" for p in stale.inline.values()), (
        stale.inline)
    assert "collective-permute" not in hidden  # jaxpr names, not HLO
    assert "ppermute" in hidden.values(), "halo refreshes missing"
    assert any(p == "all_gather" for p in hidden.values()), (
        "KV refreshes missing")
    assert len(hidden) >= 10
    # warmup/sync body: the analyzer must see its gathers as inline
    # (discrimination — the HLO negative control, full_sync, costs
    # another trace; the warmup scan body proves the same property)
    sync = min(stale_reports,
               key=lambda r: r.n_deferred + r.n_deferred_compute)
    assert sync.n_inline > 0


def test_overlap_gate_checker_clean(stale_reports):
    """The packaged checker itself passes on the shipped tree (it
    re-traces internally; the fixture just guarantees mesh setup)."""
    del stale_reports
    findings = overlap_gate.run(real_ctx())
    assert findings == [], [f.render() for f in findings]

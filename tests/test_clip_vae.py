"""CLIP text encoder parity vs transformers (torch), VAE smoke, converter tests.

The CLIP test is a true cross-framework oracle: a randomly initialized torch
CLIPTextModelWithProjection is exported via state_dict, converted with
weights.py, and our JAX forward must reproduce its hidden states, pooled
output and projected embeds.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distrifuser_tpu.models.clip import (
    CLIPTextConfig,
    clip_text_forward,
    init_clip_params,
    tiny_clip_config,
)
from distrifuser_tpu.models.vae import (
    decode,
    encode,
    init_vae_params,
    tiny_vae_config,
)
from distrifuser_tpu.models.weights import (
    convert_clip_state_dict,
    convert_unet_state_dict,
    load_params,
    save_params,
)


def test_clip_matches_transformers(tmp_path):
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.CLIPTextConfig(
        vocab_size=1000,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=77,
        projection_dim=32,
        eos_token_id=999,
        bos_token_id=998,
        hidden_act="quick_gelu",
    )
    torch.manual_seed(0)
    model = transformers.CLIPTextModelWithProjection(hf_cfg).eval()

    ids = np.random.RandomState(0).randint(0, 997, size=(2, 9))
    ids[:, 0] = 998
    ids[0, 5:] = 999  # eos mid-sequence: pooling must pick position 5
    ids[1, -1] = 999
    with torch.no_grad():
        out = model(torch.tensor(ids), output_hidden_states=True)

    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    params = convert_clip_state_dict(sd)
    cfg = CLIPTextConfig(
        vocab_size=1000, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, projection_dim=32,
        eos_token_id=999,
    )
    ours = clip_text_forward(params, cfg, ids)

    np.testing.assert_allclose(
        np.asarray(ours["last_hidden_state"]),
        out.last_hidden_state.numpy(), atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(ours["hidden_states"][-2]),
        out.hidden_states[-2].numpy(), atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(ours["text_embeds"]), out.text_embeds.numpy(), atol=2e-5
    )


def test_clip_legacy_eos_pooling_matches_transformers(tmp_path):
    """Every published SD/SDXL text_encoder config.json carries the legacy
    eos_token_id=2; transformers special-cases it by pooling at argmax(ids)
    (valid because the real EOS 49407 is the top of the CLIP vocab).  Our
    forward must reproduce that, or pooled/text_embeds silently come from
    the wrong position on real snapshots."""
    torch = pytest.importorskip("torch")
    import transformers

    hf_cfg = transformers.CLIPTextConfig(
        vocab_size=1000, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=77, projection_dim=32,
        eos_token_id=2, bos_token_id=998, hidden_act="quick_gelu",
    )
    torch.manual_seed(0)
    model = transformers.CLIPTextModelWithProjection(hf_cfg).eval()

    # "real" eos = highest vocab id (999), sitting mid-sequence; the token 2
    # also appears earlier — the ==eos_token_id rule would pool there (wrong)
    ids = np.random.RandomState(0).randint(3, 990, size=(2, 12))
    ids[:, 1] = 2
    ids[0, 5:] = 999
    ids[1, -1] = 999
    with torch.no_grad():
        out = model(torch.tensor(ids))

    params = convert_clip_state_dict(
        {k: v.numpy() for k, v in model.state_dict().items()}
    )
    cfg = CLIPTextConfig(
        vocab_size=1000, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64, projection_dim=32,
        eos_token_id=2,
    )
    ours = clip_text_forward(params, cfg, ids)
    np.testing.assert_allclose(
        np.asarray(ours["text_embeds"]), out.text_embeds.numpy(), atol=2e-5
    )


def test_clip_random_init_forward():
    cfg = tiny_clip_config()
    params = init_clip_params(jax.random.PRNGKey(0), cfg)
    ids = np.random.RandomState(1).randint(0, 1000, size=(2, 12))
    out = clip_text_forward(params, cfg, ids)
    assert out["last_hidden_state"].shape == (2, 12, 32)
    assert len(out["hidden_states"]) == cfg.num_hidden_layers + 1
    assert out["text_embeds"].shape == (2, 32)
    assert np.isfinite(np.asarray(out["last_hidden_state"])).all()


def test_vae_decode_encode_shapes():
    cfg = tiny_vae_config()
    params = init_vae_params(jax.random.PRNGKey(0), cfg)
    lat = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 8, 4))
    img = decode(params, cfg, lat)
    assert img.shape == (1, 16, 16, 3)  # 2 blocks -> one 2x upsample
    assert np.isfinite(np.asarray(img)).all()
    back = encode(params, cfg, img)
    assert back.shape == (1, 8, 8, 4)
    assert np.isfinite(np.asarray(back)).all()


def test_vae_tiled_decode_matches_full():
    cfg = tiny_vae_config()
    params = init_vae_params(jax.random.PRNGKey(0), cfg)
    lat = jax.random.normal(jax.random.PRNGKey(2), (1, 32, 8, 4))
    full = np.asarray(decode(params, cfg, lat))
    tiled = np.asarray(decode(params, cfg, lat, tile=16))
    assert tiled.shape == full.shape
    # Tiling restricts the mid-block attention to each tile (the same
    # approximation diffusers' enable_tiling makes), so boundary rows differ;
    # the bulk of pixels must still agree.
    assert np.isfinite(tiled).all()
    # 0.075, not 0.05: with random weights the mid-block attention the
    # tiling truncates is untrained noise, so the boundary effect is larger
    # than with real weights — this jax/numpy line lands at median 0.051
    assert np.median(np.abs(tiled - full)) < 0.075
    assert np.abs(tiled - full).max() < 1.5


def test_unet_converter_torch_naming_roundtrip():
    """Fake a diffusers-style state_dict for one attention + resnet and check
    the converted structure/layouts."""
    rng = np.random.RandomState(0)
    sd = {
        "conv_in.weight": rng.randn(8, 4, 3, 3).astype(np.float32),
        "conv_in.bias": rng.randn(8).astype(np.float32),
        "down_blocks.0.resnets.0.norm1.weight": rng.randn(8).astype(np.float32),
        "down_blocks.0.resnets.0.norm1.bias": rng.randn(8).astype(np.float32),
        "down_blocks.0.resnets.0.conv1.weight": rng.randn(8, 8, 3, 3).astype(np.float32),
        "down_blocks.0.resnets.0.conv1.bias": rng.randn(8).astype(np.float32),
        "down_blocks.0.resnets.0.time_emb_proj.weight": rng.randn(8, 16).astype(np.float32),
        "down_blocks.0.resnets.0.time_emb_proj.bias": rng.randn(8).astype(np.float32),
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_q.weight": rng.randn(8, 8).astype(np.float32),
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_k.weight": rng.randn(8, 8).astype(np.float32),
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_v.weight": rng.randn(8, 8).astype(np.float32),
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_out.0.weight": rng.randn(8, 8).astype(np.float32),
        "down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_out.0.bias": rng.randn(8).astype(np.float32),
        "down_blocks.0.attentions.0.transformer_blocks.0.ff.net.0.proj.weight": rng.randn(64, 8).astype(np.float32),
        "down_blocks.0.attentions.0.transformer_blocks.0.ff.net.0.proj.bias": rng.randn(64).astype(np.float32),
        "down_blocks.0.attentions.0.transformer_blocks.0.ff.net.2.weight": rng.randn(8, 32).astype(np.float32),
        "down_blocks.0.attentions.0.transformer_blocks.0.ff.net.2.bias": rng.randn(8).astype(np.float32),
    }
    p = convert_unet_state_dict(sd)
    assert p["conv_in"]["kernel"].shape == (3, 3, 4, 8)
    np.testing.assert_allclose(
        np.asarray(p["conv_in"]["kernel"]), sd["conv_in.weight"].transpose(2, 3, 1, 0)
    )
    res = p["down_blocks"][0]["resnets"][0]
    assert "scale" in res["norm1"] and res["time_emb_proj"]["kernel"].shape == (16, 8)
    attn = p["down_blocks"][0]["attentions"][0]["transformer_blocks"][0]["attn1"]
    assert "to_kv" in attn and "to_k" not in attn
    assert attn["to_kv"]["kernel"].shape == (8, 16)
    np.testing.assert_allclose(
        np.asarray(attn["to_kv"]["kernel"][:, :8]),
        sd["down_blocks.0.attentions.0.transformer_blocks.0.attn1.to_k.weight"].T,
    )
    ff = p["down_blocks"][0]["attentions"][0]["transformer_blocks"][0]["ff"]
    assert ff["net_0"]["proj"]["kernel"].shape == (8, 64)
    assert ff["net_2"]["kernel"].shape == (32, 8)


def test_params_disk_cache_roundtrip(tmp_path):
    cfg = tiny_vae_config()
    params = init_vae_params(jax.random.PRNGKey(0), cfg)
    path = str(tmp_path / "vae.npz")
    save_params(path, params)
    loaded = load_params(path)
    assert jax.tree.structure(params) == jax.tree.structure(loaded)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_loader_variant_selection(tmp_path):
    from safetensors.numpy import save_file

    from distrifuser_tpu.models.weights import load_sharded_safetensors

    d = str(tmp_path)
    save_file({"w": np.zeros((2,), np.float32)}, f"{d}/model.safetensors")
    save_file({"w": np.ones((2,), np.float16)}, f"{d}/model.fp16.safetensors")

    base = load_sharded_safetensors(d)
    assert base["w"].dtype == np.float32  # variant ignored when base exists
    fp16 = load_sharded_safetensors(d, variant="fp16")
    assert fp16["w"].dtype == np.float16
    with pytest.raises(FileNotFoundError):
        load_sharded_safetensors(d, variant="bf16")

"""convert_mmdit_state_dict: diffusers SD3 layout -> mmdit.py param tree.

No SD3 checkpoint is mountable in this image (and the pinned diffusers
0.24 predates the architecture), so these tests pin the converter's
mapping conventions against a SYNTHETIC state dict in the documented
layout: shapes land on the init tree's structure, fused qkv ordering,
the AdaLayerNormContinuous (scale, shift) -> (shift, scale) swap, and the
final block's zero-fill invariants.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distrifuser_tpu.models import mmdit as mm
from distrifuser_tpu.models.weights import convert_mmdit_state_dict

CFG = mm.tiny_mmdit_config(depth=2)


def synth_sd(seed=0, n_dual=0, cfg=None):
    cfg = cfg or CFG
    rng = np.random.RandomState(seed)
    h = cfg.hidden_size
    mlp = cfg.mlp_ratio * h
    ps, c = cfg.patch_size, cfg.in_channels
    sd = {}

    def lin(key, o, i):
        sd[f"{key}.weight"] = rng.randn(o, i).astype(np.float32) * 0.05
        sd[f"{key}.bias"] = rng.randn(o).astype(np.float32) * 0.05

    sd["pos_embed.proj.weight"] = rng.randn(h, c, ps, ps).astype(np.float32) * 0.05
    sd["pos_embed.proj.bias"] = rng.randn(h).astype(np.float32) * 0.05
    sd["pos_embed.pos_embed"] = np.zeros((1, 64 * 64, h), np.float32)  # ignored
    lin("context_embedder", h, cfg.joint_attention_dim)
    lin("time_text_embed.timestep_embedder.linear_1", h,
        cfg.frequency_embedding_size)
    lin("time_text_embed.timestep_embedder.linear_2", h, h)
    lin("time_text_embed.text_embedder.linear_1", h,
        cfg.pooled_projection_dim)
    lin("time_text_embed.text_embedder.linear_2", h, h)
    lin("norm_out.linear", 2 * h, h)
    lin("proj_out", ps * ps * cfg.out_channels, h)
    for i in range(cfg.depth):
        b = f"transformer_blocks.{i}"
        last = i == cfg.depth - 1
        dual = i < n_dual
        lin(f"{b}.norm1.linear", (9 if dual else 6) * h, h)
        lin(f"{b}.norm1_context.linear", (2 if last else 6) * h, h)
        for n in ("to_q", "to_k", "to_v"):
            lin(f"{b}.attn.{n}", h, h)
        lin(f"{b}.attn.add_k_proj", h, h)
        lin(f"{b}.attn.add_v_proj", h, h)
        lin(f"{b}.attn.to_out.0", h, h)
        lin(f"{b}.ff.net.0.proj", mlp, h)
        lin(f"{b}.ff.net.2", h, mlp)
        if dual:
            for n in ("to_q", "to_k", "to_v"):
                lin(f"{b}.attn2.{n}", h, h)
            lin(f"{b}.attn2.to_out.0", h, h)
        if not last:
            lin(f"{b}.attn.add_q_proj", h, h)
            lin(f"{b}.attn.to_add_out", h, h)
            lin(f"{b}.ff_context.net.0.proj", mlp, h)
            lin(f"{b}.ff_context.net.2", h, mlp)
    return sd


def test_converted_tree_matches_init_structure():
    sd = synth_sd()
    tree = convert_mmdit_state_dict(sd)
    ref = mm.init_mmdit_params(jax.random.PRNGKey(0), CFG)
    ref_shapes = jax.tree.map(lambda l: l.shape, ref)
    got_shapes = jax.tree.map(lambda l: tuple(np.shape(l)), tree)
    assert ref_shapes == got_shapes


def test_qkv_fusion_and_scale_shift_swap():
    sd = synth_sd()
    h = CFG.hidden_size
    tree = convert_mmdit_state_dict(sd)
    # fused x_qkv column order is (q, k, v), each transposed
    blk0 = jax.tree.map(lambda l: np.asarray(l)[0], tree["blocks"])
    np.testing.assert_array_equal(
        blk0["x_qkv"]["kernel"][:, :h],
        sd["transformer_blocks.0.attn.to_q.weight"].T)
    np.testing.assert_array_equal(
        blk0["x_qkv"]["kernel"][:, 2 * h:],
        sd["transformer_blocks.0.attn.to_v.weight"].T)
    # norm_out is AdaLayerNormContinuous (scale, shift): converted
    # final_mod must have the SHIFT rows first
    np.testing.assert_array_equal(
        np.asarray(tree["final_mod"]["kernel"])[:, :h],
        sd["norm_out.linear.weight"][h:].T)
    np.testing.assert_array_equal(
        np.asarray(tree["final_mod"]["bias"])[h:],
        sd["norm_out.linear.bias"][:h])
    # conv patch embed flattens in patchify's (p, q, c) order
    pw = sd["pos_embed.proj.weight"]
    np.testing.assert_array_equal(
        np.asarray(tree["proj_in"]["kernel"]),
        pw.transpose(2, 3, 1, 0).reshape(-1, h))


def test_final_block_zero_fill_invariants():
    sd = synth_sd()
    tree = convert_mmdit_state_dict(sd)
    h = CFG.hidden_size
    last = jax.tree.map(lambda l: np.asarray(l)[-1], tree["blocks"])
    # query third of c_qkv, context out, and context MLP are zero
    assert (last["c_qkv"]["kernel"][:, :h] == 0).all()
    assert (last["c_qkv"]["kernel"][:, h:] != 0).any()
    assert (last["c_out"]["kernel"] == 0).all()
    assert (last["c_fc1"]["kernel"] == 0).all()
    # c_mod: (shift, scale) populated from the continuous norm (swapped),
    # gates and MLP chunks zero -> the final context residual is exact
    cm = last["c_mod"]["kernel"]
    np.testing.assert_array_equal(
        cm[:, :h], sd["transformer_blocks.1.norm1_context.linear.weight"][h:].T)
    assert (cm[:, 2 * h:] == 0).all()
    # non-final block keeps a full context stream
    first = jax.tree.map(lambda l: np.asarray(l)[0], tree["blocks"])
    assert (first["c_out"]["kernel"] != 0).any()


def test_converted_forward_runs():
    tree = convert_mmdit_state_dict(synth_sd())
    k = jax.random.PRNGKey(1)
    x = jax.random.normal(k, (1, CFG.sample_size, CFG.sample_size,
                              CFG.in_channels))
    enc = jax.random.normal(jax.random.fold_in(k, 1),
                            (1, 6, CFG.joint_attention_dim))
    pooled = jax.random.normal(jax.random.fold_in(k, 2),
                               (1, CFG.pooled_projection_dim))
    out = mm.mmdit_forward(tree, CFG, x, jnp.asarray(400.0), enc, pooled)
    assert out.shape == x.shape[:3] + (CFG.out_channels,)
    assert np.isfinite(np.asarray(out)).all()


def test_dual_attention_convert():
    """SD3.5-medium layout: attn2 + 9-chunk AdaLayerNormZeroX on the dual
    prefix converts onto the blocks_dual layout; x_mod keeps the FIRST 6
    chunks and x_mod2 gets the LAST 3; non-prefix layouts are rejected."""
    import dataclasses

    cfg = dataclasses.replace(CFG, depth=3, dual_attention_blocks=2)
    sd = synth_sd(n_dual=2, cfg=cfg)
    tree = convert_mmdit_state_dict(sd)
    ref = mm.init_mmdit_params(jax.random.PRNGKey(0), cfg)
    assert (jax.tree.map(lambda l: tuple(np.shape(l)), tree)
            == jax.tree.map(lambda l: l.shape, ref))
    h = cfg.hidden_size
    w9 = sd["transformer_blocks.0.norm1.linear.weight"]
    b9 = sd["transformer_blocks.0.norm1.linear.bias"]
    blk0 = jax.tree.map(lambda l: np.asarray(l)[0], tree["blocks"])
    d0 = jax.tree.map(lambda l: np.asarray(l)[0], tree["blocks_dual"])
    np.testing.assert_array_equal(blk0["x_mod"]["kernel"], w9[:6 * h].T)
    np.testing.assert_array_equal(d0["x_mod2"]["kernel"], w9[6 * h:].T)
    np.testing.assert_array_equal(d0["x_mod2"]["bias"], b9[6 * h:])
    np.testing.assert_array_equal(
        d0["x2_qkv"]["kernel"][:, :h],
        sd["transformer_blocks.0.attn2.to_q.weight"].T)
    np.testing.assert_array_equal(
        d0["x2_out"]["kernel"],
        sd["transformer_blocks.0.attn2.to_out.0.weight"].T)
    # converted tree runs end-to-end
    out = mm.mmdit_forward(
        tree, cfg,
        jnp.zeros((1, cfg.sample_size, cfg.sample_size, cfg.in_channels)),
        jnp.asarray(300.0),
        jnp.zeros((1, 5, cfg.joint_attention_dim)),
        jnp.zeros((1, cfg.pooled_projection_dim)),
    )
    assert np.isfinite(np.asarray(out)).all()
    # a non-prefix dual layout (attn2 on block 1 only) is rejected
    bad = {k: v for k, v in sd.items()
           if not (k.startswith("transformer_blocks.0.attn2")
                   or k.startswith("transformer_blocks.0.norm1.linear"))}
    bad["transformer_blocks.0.norm1.linear.weight"] = (
        np.zeros((6 * h, h), np.float32))
    bad["transformer_blocks.0.norm1.linear.bias"] = (
        np.zeros((6 * h,), np.float32))
    for n in ("to_q", "to_k", "to_v"):
        bad[f"transformer_blocks.1.attn2.{n}.weight"] = (
            np.zeros((h, h), np.float32))
        bad[f"transformer_blocks.1.attn2.{n}.bias"] = (
            np.zeros((h,), np.float32))
    bad["transformer_blocks.1.attn2.to_out.0.weight"] = (
        np.zeros((h, h), np.float32))
    bad["transformer_blocks.1.attn2.to_out.0.bias"] = (
        np.zeros((h,), np.float32))
    import pytest

    with pytest.raises(ValueError, match="contiguous-prefix"):
        convert_mmdit_state_dict(bad)


def test_qk_norm_keys_convert(tmp_path):
    """SD3.5-layout snapshots (attn.norm_q/_k + norm_added_q/_k) convert
    onto the qk_norm param layout; the final block's absent context
    q-norm is filled with ones (its output rows are discarded)."""
    import dataclasses

    sd = synth_sd()
    h = CFG.hidden_size
    d = h // CFG.num_heads
    rng = np.random.RandomState(9)
    for i in range(CFG.depth):
        b = f"transformer_blocks.{i}"
        sd[f"{b}.attn.norm_q.weight"] = rng.rand(d).astype(np.float32)
        sd[f"{b}.attn.norm_k.weight"] = rng.rand(d).astype(np.float32)
        sd[f"{b}.attn.norm_added_k.weight"] = rng.rand(d).astype(np.float32)
        if i != CFG.depth - 1:  # context_pre_only final block: no added_q
            sd[f"{b}.attn.norm_added_q.weight"] = rng.rand(d).astype(
                np.float32)
    tree = convert_mmdit_state_dict(sd)
    qcfg = dataclasses.replace(CFG, qk_norm=True)
    ref = mm.init_mmdit_params(jax.random.PRNGKey(0), qcfg)
    assert (jax.tree.map(lambda l: tuple(np.shape(l)), tree)
            == jax.tree.map(lambda l: l.shape, ref))
    last = jax.tree.map(lambda l: np.asarray(l)[-1], tree["blocks"])
    np.testing.assert_array_equal(last["c_qnorm"], 1.0)
    np.testing.assert_array_equal(
        last["x_qnorm"],
        sd[f"transformer_blocks.{CFG.depth - 1}.attn.norm_q.weight"])
    # converted qk-norm params run end-to-end
    out = mm.mmdit_forward(
        tree, qcfg,
        jnp.zeros((1, qcfg.sample_size, qcfg.sample_size,
                   qcfg.in_channels)),
        jnp.asarray(300.0),
        jnp.zeros((1, 5, qcfg.joint_attention_dim)),
        jnp.zeros((1, qcfg.pooled_projection_dim)),
    )
    assert np.isfinite(np.asarray(out)).all()

"""Displaced patch parallelism on the DiT (parallel/dit_sp.py).

Oracle: per-patch sequential evaluation with per-block gathered KV caches —
step s attends over step s-1's cache with the patch's own rows fresh
(pp/attn.py:135-140 semantics), and the cache refreshes to step s's fresh
K/V afterwards.  Patches are independent within a stale step, so the oracle
runs them one by one on a single device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distrifuser_tpu.models import dit as dit_mod
from distrifuser_tpu.parallel.dit_sp import DiTDenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.utils.config import DistriConfig

from test_pipefusion import dense_loop, make_inputs, make_model


def oracle_displaced(params, dcfg, sched, latents, enc, gs, num_steps,
                     warmup_steps, n, do_cfg=True, refresh=True):
    sched.set_timesteps(num_steps)
    ts = sched.timesteps()
    x = dit_mod.patchify(dcfg, latents.astype(jnp.float32))
    batch, n_tok, _ = x.shape
    chunk = n_tok // n
    n_sync = min(warmup_steps + 1, num_steps)
    hid = dcfg.hidden_size
    pos = dit_mod.pos_embed_table(dcfg, jnp.float32)
    branches = (0, 1) if do_cfg else (0,)

    cap_kv = {br: dit_mod.precompute_caption_kv(params, dcfg, enc[br])
              for br in branches}
    cache = {br: [(jnp.zeros((batch, n_tok, hid)),
                   jnp.zeros((batch, n_tok, hid)))
                  for _ in range(dcfg.depth)] for br in branches}
    sstate = sched.init_state(x.shape)

    def blocks(br, tokens, s, assemble_for):
        """Run the stack on `tokens`; assemble_for(l, k, v) -> (K, V).
        Returns (eps_tokens, fresh [list over blocks of (k, v)])."""
        temb = dit_mod.t_embed(params, dcfg, ts[s])
        c6 = dit_mod.adaln_table(params, dcfg, temb)
        start = assemble_for["offset"]
        pos_rows = jax.lax.dynamic_slice_in_dim(pos, start, tokens.shape[1], 0)
        h = dit_mod.embed_tokens(params, dcfg, tokens, pos_rows)
        fresh = []
        for l in range(dcfg.depth):
            bp = jax.tree.map(lambda a: a[l], params["blocks"])

            def assemble(k, v, l=l):
                if assemble_for["sync"]:
                    return k, v  # full-seq tokens: fresh IS the full KV
                ck, cv = cache[br][l]
                return (
                    jax.lax.dynamic_update_slice(ck, k, (0, start, 0)),
                    jax.lax.dynamic_update_slice(cv, v, (0, start, 0)),
                )

            h, (k, v) = dit_mod.dit_block(bp, dcfg, h, c6, cap_kv[br][l],
                                          kv_assemble=assemble)
            fresh.append((k, v))
        return dit_mod.final_layer(params, dcfg, h, temb), fresh

    def combine(eps):
        if not do_cfg:
            return eps[0]
        return eps[0] + gs * (eps[1] - eps[0])

    for s in range(num_steps):
        x_in = sched.scale_model_input(x, s)
        if s < n_sync:
            eps, fr = {}, {}
            for br in branches:
                eps[br], fr[br] = blocks(
                    br, x_in, s, {"sync": True, "offset": 0}
                )
                cache[br] = fr[br]
        else:
            eps = {br: [] for br in branches}
            fresh_all = {br: [[] for _ in range(dcfg.depth)] for br in branches}
            for p in range(n):
                rows = x_in[:, p * chunk:(p + 1) * chunk]
                for br in branches:
                    e, fr = blocks(
                        br, rows, s, {"sync": False, "offset": p * chunk}
                    )
                    eps[br].append(e)
                    for l in range(dcfg.depth):
                        fresh_all[br][l].append(fr[l])
            eps = {br: jnp.concatenate(v, axis=1) for br, v in eps.items()}
            if refresh:
                for br in branches:
                    cache[br] = [
                        (jnp.concatenate([kv[0] for kv in fresh_all[br][l]], axis=1),
                         jnp.concatenate([kv[1] for kv in fresh_all[br][l]], axis=1))
                        for l in range(dcfg.depth)
                    ]
        x, sstate = sched.step(x, combine(eps).astype(jnp.float32), s, sstate)

    return dit_mod.unpatchify(dcfg, x, dcfg.in_channels)


def sp_config(n_dev, do_cfg, **kw):
    return DistriConfig(
        devices=jax.devices()[:n_dev], height=128, width=128,
        do_classifier_free_guidance=do_cfg, split_batch=do_cfg, **kw,
    )


def test_full_sync_matches_dense():
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    cfg = sp_config(4, do_cfg=False, mode="full_sync")
    runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out = runner.generate(lat, enc, guidance_scale=1.0, num_inference_steps=3)
    ref = dense_loop(params, dcfg, get_scheduler("ddim"), lat, enc, 1.0, 3,
                     do_cfg=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("scheduler", ["ddim", "dpm-solver"])
def test_displaced_matches_oracle(scheduler):
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    cfg = sp_config(4, do_cfg=False, warmup_steps=1)
    runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler(scheduler))
    out = runner.generate(lat, enc, guidance_scale=1.0, num_inference_steps=6)
    ref = oracle_displaced(
        params, dcfg, get_scheduler(scheduler), lat, enc, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_cfg_split_composes():
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    cfg = sp_config(8, do_cfg=True, warmup_steps=1)
    assert cfg.cfg_split and cfg.n_device_per_batch == 4
    runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out = runner.generate(lat, enc, guidance_scale=3.5, num_inference_steps=5)
    ref = oracle_displaced(
        params, dcfg, get_scheduler("ddim"), lat, enc, 3.5, 5,
        warmup_steps=1, n=4, do_cfg=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_cfg_folded():
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    cfg = DistriConfig(
        devices=jax.devices()[:2], height=128, width=128,
        do_classifier_free_guidance=True, split_batch=False, warmup_steps=1,
    )
    runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out = runner.generate(lat, enc, guidance_scale=3.5, num_inference_steps=4)
    ref = oracle_displaced(
        params, dcfg, get_scheduler("ddim"), lat, enc, 3.5, 4,
        warmup_steps=1, n=2, do_cfg=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_no_sync_mode():
    """mode='no_sync': the KV state freezes at the warmup snapshot."""
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    cfg = sp_config(4, do_cfg=False, warmup_steps=1, mode="no_sync")
    runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out = runner.generate(lat, enc, guidance_scale=1.0, num_inference_steps=6)
    ref = oracle_displaced(
        params, dcfg, get_scheduler("ddim"), lat, enc, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False, refresh=False,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    # and it must NOT equal the refreshing path
    ref_refresh = oracle_displaced(
        params, dcfg, get_scheduler("ddim"), lat, enc, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False, refresh=True,
    )
    assert not np.allclose(np.asarray(out), np.asarray(ref_refresh),
                           rtol=2e-4, atol=2e-4)


def test_ring_matches_gather():
    """attn_impl='ring': O(L/n) state, same displaced numerics as 'gather'
    (online softmax vs plain softmax differ only in rounding)."""
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    out = {}
    for impl in ("gather", "ring"):
        cfg = sp_config(4, do_cfg=False, warmup_steps=1, attn_impl=impl)
        runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
        out[impl] = np.asarray(
            runner.generate(lat, enc, guidance_scale=1.0, num_inference_steps=6)
        )
    np.testing.assert_allclose(out["ring"], out["gather"], rtol=2e-4, atol=2e-4)


def test_ring_no_sync_matches_gather_no_sync():
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    out = {}
    for impl in ("gather", "ring"):
        cfg = sp_config(4, do_cfg=False, warmup_steps=1, attn_impl=impl,
                        mode="no_sync")
        runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
        out[impl] = np.asarray(
            runner.generate(lat, enc, guidance_scale=1.0, num_inference_steps=5)
        )
    np.testing.assert_allclose(out["ring"], out["gather"], rtol=2e-4, atol=2e-4)


def test_ulysses_exact():
    """attn_impl='ulysses' is exact: equals the dense loop at EVERY step
    count and warmup setting (no staleness exists)."""
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    cfg = sp_config(4, do_cfg=False, warmup_steps=0, attn_impl="ulysses")
    runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out = runner.generate(lat, enc, guidance_scale=1.0, num_inference_steps=5)
    ref = dense_loop(params, dcfg, get_scheduler("ddim"), lat, enc, 1.0, 5,
                     do_cfg=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_cfg_split():
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    cfg = sp_config(8, do_cfg=True, warmup_steps=0, attn_impl="ulysses")
    runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out = runner.generate(lat, enc, guidance_scale=3.5, num_inference_steps=4)
    ref = dense_loop(params, dcfg, get_scheduler("ddim"), lat, enc, 3.5, 4,
                     do_cfg=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_head_divisibility():
    dcfg, params = make_model()  # 4 heads
    with pytest.raises(ValueError, match="num_heads"):
        DiTDenoiseRunner(
            sp_config(8, do_cfg=False, attn_impl="ulysses"),
            dcfg, params, get_scheduler("ddim"),
        )


def test_rejected_knobs():
    dcfg, params = make_model()
    with pytest.raises(ValueError, match="comm_batch"):
        DiTDenoiseRunner(sp_config(4, do_cfg=False, comm_batch=True),
                         dcfg, params, get_scheduler("ddim"))


def test_geometry_validation():
    dcfg, params = make_model()
    with pytest.raises(ValueError, match="sample_size"):
        DiTDenoiseRunner(
            DistriConfig(devices=jax.devices()[:4], height=256, width=256,
                         do_classifier_free_guidance=False, split_batch=False),
            dcfg, params, get_scheduler("ddim"),
        )


@pytest.mark.parametrize("u", [1, 2, 4])
def test_usp_exact(u):
    """attn_impl='usp' (Ulysses x ring 2-level SP) is exact for every
    factorization of the sp axis: u=4/r=1 degenerates to pure head-sharding,
    u=1/r=4 to the exact KV ring, u=2/r=2 is the genuine composition.  All
    must equal the dense loop (no staleness exists in this layout)."""
    dcfg, params = make_model()  # 4 heads
    lat, enc = make_inputs(dcfg)
    cfg = sp_config(4, do_cfg=False, warmup_steps=0, attn_impl="usp",
                    ulysses_degree=u)
    runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out = runner.generate(lat, enc, guidance_scale=1.0, num_inference_steps=4)
    ref = dense_loop(params, dcfg, get_scheduler("ddim"), lat, enc, 1.0, 4,
                     do_cfg=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_usp_cfg_split():
    """USP under the CFG mesh axis: 8 devices = cfg 2 x (sp_u 2 x sp_r 2)."""
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    cfg = sp_config(8, do_cfg=True, warmup_steps=0, attn_impl="usp",
                    ulysses_degree=2)
    runner = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out = runner.generate(lat, enc, guidance_scale=3.5, num_inference_steps=4)
    ref = dense_loop(params, dcfg, get_scheduler("ddim"), lat, enc, 3.5, 4,
                     do_cfg=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_usp_validation():
    dcfg, _ = make_model()  # 4 heads
    with pytest.raises(ValueError, match="ulysses_degree"):
        sp_config(8, do_cfg=False, attn_impl="usp", ulysses_degree=3)
    with pytest.raises(ValueError, match="ulysses_degree applies"):
        sp_config(8, do_cfg=False, attn_impl="ring", ulysses_degree=2)


def test_usp_rejected_by_unet_runner():
    from distrifuser_tpu.models.unet import init_unet_params, tiny_config
    from distrifuser_tpu.parallel.runner import DenoiseRunner

    ucfg = tiny_config()
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    cfg = DistriConfig(devices=jax.devices()[:4], height=128, width=128,
                       do_classifier_free_guidance=False, split_batch=False,
                       attn_impl="usp", ulysses_degree=2)
    with pytest.raises(ValueError, match="DiT strategy"):
        DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))


def test_comm_report_layouts():
    """The layout trade the report must show: ring state is n-x smaller than
    gather; ulysses/usp are stateless; usp's ring traffic shrinks with
    ulysses_degree."""
    dcfg, params = make_model()
    reports = {}
    for impl, kw in [("gather", {}), ("ring", {}), ("ulysses", {}),
                     ("usp", {"ulysses_degree": 2})]:
        cfg = sp_config(4, do_cfg=False, attn_impl=impl, **kw)
        r = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
        reports[impl] = r.comm_report()
    assert reports["gather"]["kv_state_elems"] == \
        4 * reports["ring"]["kv_state_elems"]
    assert reports["ulysses"]["kv_state_elems"] == 0
    assert reports["usp"]["kv_state_elems"] == 0
    # at n=4/u=2 the two layouts move identical bytes (1.5*N*hid per
    # block); usp's advantage is strict from n=8 up
    assert (reports["usp"]["per_step_collective_elems"]
            == reports["ring"]["per_step_collective_elems"])
    r8 = {}
    for impl, kw in [("ring", {}), ("usp", {"ulysses_degree": 2})]:
        cfg = sp_config(8, do_cfg=False, attn_impl=impl, **kw)
        r8[impl] = DiTDenoiseRunner(
            cfg, dcfg, params, get_scheduler("ddim")).comm_report()
    assert (r8["usp"]["per_step_collective_elems"]
            < r8["ring"]["per_step_collective_elems"])
    # single device: no collectives at all
    cfg1 = sp_config(1, do_cfg=False)
    r1 = DiTDenoiseRunner(cfg1, dcfg, params, get_scheduler("ddim"))
    assert r1.comm_report()["per_step_collective_elems"] == 0


@pytest.mark.parametrize("impl,sched", [
    ("gather", "ddim"),
    ("ring", "ddim"),
    ("gather", "dpm-solver"),  # scheduler state crosses the hybrid boundary
    ("usp", "ddim"),           # factored sp_u x sp_r mesh axes in kv_spec
])
def test_hybrid_matches_fused(impl, sched):
    """cfg.hybrid_loop (two one-body programs, carry across the jit
    boundary) must equal the fused two-body loop."""
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    kw = dict(attn_impl=impl, warmup_steps=1)
    if impl == "usp":
        kw["ulysses_degree"] = 2
    fused = DiTDenoiseRunner(sp_config(4, do_cfg=True, **kw), dcfg, params,
                             get_scheduler(sched))
    hybrid = DiTDenoiseRunner(sp_config(4, do_cfg=True, hybrid_loop=True,
                                        **kw), dcfg, params,
                              get_scheduler(sched))
    a = np.asarray(fused.generate(lat, enc, guidance_scale=4.0,
                                  num_inference_steps=5))
    b = np.asarray(hybrid.generate(lat, enc, guidance_scale=4.0,
                                   num_inference_steps=5))
    np.testing.assert_allclose(a, b, atol=2e-4)


def test_hybrid_all_sync_short_run():
    """Runs where every step is sync take the plain fused path (the hybrid
    gate requires a non-empty stale tail)."""
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    r = DiTDenoiseRunner(sp_config(4, do_cfg=True, hybrid_loop=True,
                                   warmup_steps=4), dcfg, params,
                         get_scheduler("ddim"))
    out = r.generate(lat, enc, guidance_scale=4.0, num_inference_steps=2)
    assert np.isfinite(np.asarray(out)).all()


def test_stepwise_matches_fused():
    """use_cuda_graph=False parity for the DiT runner: host-driven per-step
    programs equal the fused loop across the attention layouts (the
    stateless-ulysses placeholder KV crosses the boundary too)."""
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)
    kw = dict(guidance_scale=1.0, num_inference_steps=4)
    for extra in ({}, {"attn_impl": "ring"}, {"attn_impl": "ulysses"}):
        fused = DiTDenoiseRunner(
            sp_config(4, do_cfg=False, warmup_steps=1, **extra),
            dcfg, params, get_scheduler("ddim"))
        stepw = DiTDenoiseRunner(
            sp_config(4, do_cfg=False, warmup_steps=1, use_cuda_graph=False,
                      **extra),
            dcfg, params, get_scheduler("ddim"))
        a = np.asarray(fused.generate(lat, enc, **kw))
        b = np.asarray(stepw.generate(lat, enc, **kw))
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4,
                                   err_msg=str(extra))


def test_callback_all_modes():
    """The diffusers legacy callback fires with identical count, order,
    timesteps, and latents from the host loop and from inside the
    compiled loop (ordered io_callback)."""
    dcfg, params = make_model()
    lat, enc = make_inputs(dcfg)

    def run(runner):
        seen = []
        out = runner.generate(
            lat, enc, guidance_scale=1.0, num_inference_steps=4,
            callback=lambda i, t, x: seen.append(
                (int(i), float(t), np.array(x, copy=True))),
        )
        return seen, np.asarray(out)

    stepw = DiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=1, use_cuda_graph=False),
        dcfg, params, get_scheduler("ddim"))
    fused = DiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=1),
        dcfg, params, get_scheduler("ddim"))
    s_seen, s_out = run(stepw)
    f_seen, f_out = run(fused)
    assert [i for i, _, _ in s_seen] == [0, 1, 2, 3]
    assert [i for i, _, _ in f_seen] == [i for i, _, _ in s_seen]
    assert [t for _, t, _ in f_seen] == [t for _, t, _ in s_seen]
    for (_, _, xa), (_, _, xb) in zip(f_seen, s_seen):
        np.testing.assert_allclose(xa, xb, atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(f_out, s_out, atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(f_seen[-1][2], f_out, atol=0)


def test_pipefusion_rejects_callbacks():
    from test_pipefusion import make_inputs as pf_inputs
    from test_pipefusion import make_model as pf_model
    from distrifuser_tpu.parallel.pipefusion import PipeFusionRunner

    dcfg, params = pf_model()
    lat, enc = pf_inputs(dcfg)
    runner = PipeFusionRunner(
        DistriConfig(devices=jax.devices()[:4], height=128, width=128),
        dcfg, params, get_scheduler("ddim"))
    with pytest.raises(ValueError, match="token"):
        runner.generate(lat, enc, num_inference_steps=2,
                        callback=lambda i, t, x: None)


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

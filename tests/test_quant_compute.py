"""Quantized COMPUTE (ISSUE 12): int8/fp8 matmuls as an execution path.

Covers: per-family parity of the low-precision dot/Pallas paths vs the
PR-6 dequant-bf16 fallback (pinned tolerances), the HLO-level guarantee
that a compute-routed transformer block runs an int8 ``dot`` with NO
dequantize-to-float convert feeding it, GEMM routing resolution order
(env override -> forced policy -> measured table with backend gating ->
analytic default), the Pallas kernel's bit-parity with the XLA dot route,
channel-tile scale grouping, and ExecKey distinctness across
(none / int8-storage / int8-compute).
"""

import dataclasses
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distrifuser_tpu.models import dit as dit_mod
from distrifuser_tpu.models import mmdit as mmdit_mod
from distrifuser_tpu.models import unet as unet_mod
from distrifuser_tpu.models.weights import quantize_params, set_quant_compute
from distrifuser_tpu.ops import gemm_routing
from distrifuser_tpu.ops.gemm_routing import GemmRoute, resolve
from distrifuser_tpu.ops.linear import linear
from distrifuser_tpu.ops.quant_matmul import quant_matmul
from distrifuser_tpu.parallel.compress import (
    QuantizedTensor,
    fp8_supported,
    quantize,
    quantize_weight,
    validate_quant_compute,
)
from distrifuser_tpu.serve import ExecKey

MODES = ["int8"] + (["fp8"] if fp8_supported() else [])

# Pinned compute-path tolerances: max |Δ| of the raw tiny-model forward vs
# the DENSE forward (fixed seeds below).  The low-precision paths quantize
# ACTIVATIONS too (dynamic per-token), so their budget sits above the
# storage-only dequant numbers but within ~2x of them — the relative
# assertion below pins that ratio, these absolute ceilings pin the scale.
TOL_COMPUTE = {
    "int8": {"unet": 0.12, "dit": 0.02, "mmdit": 0.025},
    "fp8": {"unet": 0.5, "dit": 0.09, "mmdit": 0.12},
}


# --------------------------------------------------------------------------
# family forwards (tiny configs, fixed seeds)
# --------------------------------------------------------------------------


def _family_forward(family):
    """(params, forward(params) -> array) for one tiny family model."""
    k = jax.random.PRNGKey(1)
    if family == "unet":
        cfg = unet_mod.tiny_config(sdxl=False)
        p = unet_mod.init_unet_params(jax.random.PRNGKey(0), cfg)
        sample = jax.random.normal(k, (2, 16, 16, cfg.in_channels))
        enc = jax.random.normal(
            jax.random.fold_in(k, 1), (2, 7, cfg.cross_attention_dim))
        t = jnp.array([7.0, 7.0])
        return p, lambda q: unet_mod.unet_forward(q, cfg, sample, t, enc)
    if family == "dit":
        cfg = dit_mod.tiny_dit_config(depth=4)
        p = dit_mod.init_dit_params(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(k, (1, 16, 16, 4))
        enc = jax.random.normal(
            jax.random.fold_in(k, 2), (1, 9, cfg.caption_dim))
        return p, lambda q: dit_mod.dit_forward(
            q, cfg, x, jnp.asarray(500.0), enc)
    assert family == "mmdit"
    cfg = mmdit_mod.tiny_mmdit_config()
    p = mmdit_mod.init_mmdit_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(
        k, (2, cfg.sample_size, cfg.sample_size, cfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 7, cfg.joint_attention_dim))
    pooled = jax.random.normal(
        jax.random.fold_in(k, 2), (2, cfg.pooled_projection_dim))
    return p, lambda q: mmdit_mod.mmdit_forward(
        q, cfg, x, jnp.asarray(500.0), enc, pooled)


@pytest.mark.parametrize("family", ["unet", "dit", "mmdit"])
@pytest.mark.parametrize("mode", MODES)
def test_family_compute_path_parity(family, mode):
    """int8/fp8 matmul execution stays within the pinned tolerance of the
    dense forward on every family, and within 2x of the storage-only
    dequant path's error (the compute path adds activation quantization,
    not a different weight rounding)."""
    params, fwd = _family_forward(family)
    dense = np.asarray(fwd(params), np.float64)
    dq = np.asarray(
        fwd(quantize_params(params, mode, compute="dequant")), np.float64)
    dot = np.asarray(
        fwd(quantize_params(params, mode, compute="dot")), np.float64)
    err_dq = np.abs(dq - dense).max()
    err_dot = np.abs(dot - dense).max()
    assert err_dot <= TOL_COMPUTE[mode][family], (family, mode, err_dot)
    assert err_dot <= 2.0 * err_dq + 1e-6, (
        f"{family}/{mode}: compute path error {err_dot} is more than 2x "
        f"the storage-only error {err_dq}"
    )


@pytest.mark.parametrize("mode", MODES)
def test_pallas_route_matches_dot_route_bitwise(mode):
    """The Pallas kernel is the SAME arithmetic as the XLA dot route
    (int32/fp32 accumulate, scales after) — on the DiT family forward the
    two routes agree bit-for-bit in fp32."""
    params, fwd = _family_forward("dit")
    dot = np.asarray(fwd(quantize_params(params, mode, compute="dot")))
    pal = np.asarray(fwd(quantize_params(params, mode, compute="pallas")))
    np.testing.assert_allclose(pal, dot, atol=2e-6)


def test_quant_matmul_kernel_parity_and_padding():
    """Direct kernel check: odd M/K/N (forcing the pad path) and partial
    channel tiles still reproduce the reference int8 GEMM exactly."""
    rng = np.random.RandomState(3)
    for m, k, n, ct in [(64, 64, 48, 1), (33, 72, 50, 16), (128, 256, 130, 64)]:
        w = jnp.asarray(rng.randn(k, n).astype(np.float32))
        qt = quantize_weight(w, "int8", channel_tile=ct)
        x = jnp.asarray(rng.randn(m, k).astype(np.float32))
        xq, sx = quantize(x, "int8", axis=-1)
        sw = qt.channel_scale()
        ref = jax.lax.dot_general(
            xq, qt.payload, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32) * sw
        got = quant_matmul(xq, qt.payload, sw, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_channel_tile_partial_last_tile_roundtrip():
    """channel_tile grouping: scale length is ceil(N/tile) (partial last
    tile), dequantization expands it back per channel, and the error stays
    bounded by the TILE amax."""
    rng = np.random.RandomState(5)
    w = jnp.asarray(rng.randn(32, 50).astype(np.float32))
    qt = quantize_weight(w, "int8", channel_tile=16)
    assert qt.scale.shape == (4,)  # ceil(50/16)
    back = np.asarray(qt.__jax_array__(), np.float64)
    amax = np.abs(np.asarray(w, np.float64)).max(axis=0)
    tile_amax = np.array([
        amax[i * 16:(i + 1) * 16].max() for i in range(4)])
    bound = np.repeat(tile_amax, 16)[:50] / 254.0
    assert (np.abs(back - np.asarray(w, np.float64)) <= bound + 1e-7).all()
    # a misaligned rebuild (the pre-fix loader bug: tile size dropped ->
    # per-channel assumed) refuses loudly instead of dequantizing with
    # wrong scales
    with pytest.raises(ValueError, match="misalignment"):
        QuantizedTensor(qt.payload, qt.scale, qt.dtype)


# --------------------------------------------------------------------------
# HLO: the hot path really runs an int8 dot, with no dequant convert
# --------------------------------------------------------------------------


_DEF = re.compile(r"^\s*(?:ROOT )?%?([\w.\-]+) = (\w+)\[")
_OPERANDS = re.compile(r"\(([^)]*)\)")


def _hlo_defs(hlo):
    """{instr name: (result type prefix, opcode, [operand names])}."""
    defs = {}
    for line in hlo.splitlines():
        m = _DEF.match(line)
        if not m or " = " not in line:
            continue
        name, ty = m.group(1), m.group(2)
        rhs = line.split(" = ", 1)[1]
        op = rhs.split("[", 1)[0].strip() if "[" in rhs else ""
        opcode = re.match(r"\w+\[[^\]]*\]\{?[^ ]* (\w[\w\-]*)\(", rhs)
        opcode = opcode.group(1) if opcode else rhs.split("(", 1)[0].split()[-1]
        args = []
        paren = rhs.find("(")
        if paren >= 0:
            depth, j = 0, paren
            for j, ch in enumerate(rhs[paren:], start=paren):
                depth += ch == "("
                depth -= ch == ")"
                if depth == 0:
                    break
            for tok in rhs[paren + 1:j].split(","):
                tok = tok.strip().lstrip("%")
                # operands print either as bare names or as "type name"
                args.append(tok.split()[-1].lstrip("%") if tok else tok)
        defs[name] = (ty, opcode, args)
    return defs


_PASSTHROUGH = frozenset({
    "multiply", "add", "subtract", "broadcast", "reshape", "transpose",
    "convert", "copy", "slice", "concatenate", "pad", "negate",
})


def _dequant_feeds_a_dot(hlo) -> bool:
    """True when some float dot consumes (transitively through elementwise
    / data movement) a convert FROM an integer-quantized value TO float —
    the storage-only lazy-dequant signature."""
    defs = _hlo_defs(hlo)
    tainted = set()
    changed = True
    while changed:
        changed = False
        for name, (ty, opcode, args) in defs.items():
            if name in tainted:
                continue
            if opcode == "convert" and ty.startswith(("f", "bf")):
                src = defs.get(args[0]) if args else None
                if src and src[0] == "s8":
                    tainted.add(name)
                    changed = True
                    continue
            if opcode in _PASSTHROUGH and any(a in tainted for a in args):
                tainted.add(name)
                changed = True
    return any(
        opcode == "dot" and ty.startswith(("f", "bf"))
        and any(a in tainted for a in args)
        for ty, opcode, args in defs.values()
    )


def _int8_dot_present(hlo) -> bool:
    defs = _hlo_defs(hlo)
    return any(
        opcode == "dot"
        and sum(1 for a in args if defs.get(a, ("",))[0] == "s8") >= 2
        for ty, opcode, args in defs.values()
    )


def _lowered_block_hlo(compute):
    """Lowered (pre-optimization) HLO of one quantized DiT transformer
    block — the serving hot path's repeating unit."""
    cfg = dit_mod.tiny_dit_config(depth=2)
    params = quantize_params(
        dit_mod.init_dit_params(jax.random.PRNGKey(0), cfg),
        "int8", compute=compute)
    bp = jax.tree.map(lambda l: l[0], params["blocks"])
    h = jnp.zeros((1, 64, cfg.hidden_size))
    c6 = jnp.zeros((6, cfg.hidden_size))
    kv = jnp.zeros((1, 9, 2 * cfg.hidden_size))

    def block(bp, h, c6, kv):
        out, _ = dit_mod.dit_block(bp, cfg, h, c6, kv)
        return out

    return jax.jit(block).lower(bp, h, c6, kv).as_text(dialect="hlo")


def test_block_hlo_int8_dot_and_no_dequant_convert():
    """Acceptance: with compute routing forced on, the transformer block's
    lowered HLO contains an int8 ``dot`` and NO dequantize-to-float
    convert feeding any dot; the storage-only program shows exactly the
    opposite (the discrimination control)."""
    hot = _lowered_block_hlo("dot")
    assert _int8_dot_present(hot), "no s8 x s8 dot in the compute-routed block"
    assert not _dequant_feeds_a_dot(hot), (
        "compute-routed block still dequantizes a kernel into a float dot"
    )
    cold = _lowered_block_hlo("dequant")
    assert not _int8_dot_present(cold)
    assert _dequant_feeds_a_dot(cold), (
        "control lost discrimination: storage-only block shows no "
        "dequant-convert-fed dot"
    )


# --------------------------------------------------------------------------
# routing resolution
# --------------------------------------------------------------------------


def test_resolve_order_env_policy_table_analytic(monkeypatch):
    # forced policies win over everything but env
    assert resolve("int8", 4096, 64, 64, "dequant").impl == "dequant"
    assert resolve("int8", 4096, 64, 64, "dot").impl == "dot"
    assert resolve("int8", 4096, 64, 64, "pallas").impl == "pallas"
    # env overrides even a forced policy (the operator escape hatch)
    monkeypatch.setenv("DISTRIFUSER_TPU_GEMM", "0")
    assert resolve("int8", 4096, 64, 64, "dot").impl == "dequant"
    monkeypatch.setenv("DISTRIFUSER_TPU_GEMM", "pallas")
    monkeypatch.setenv("DISTRIFUSER_TPU_GEMM_BM", "64")
    r = resolve("int8", 4096, 64, 64, "dequant")
    assert r.impl == "pallas" and r.block_m == 64
    monkeypatch.setenv("DISTRIFUSER_TPU_GEMM", "nope")
    with pytest.raises(ValueError, match="DISTRIFUSER_TPU_GEMM"):
        resolve("int8", 4096, 64, 64, "auto")
    monkeypatch.delenv("DISTRIFUSER_TPU_GEMM")
    monkeypatch.delenv("DISTRIFUSER_TPU_GEMM_BM")
    # analytic defaults: dequant on cpu; dot on tpu above the M floor
    assert resolve("int8", 4096, 64, 64, "auto", platform="cpu").impl == "dequant"
    assert resolve("int8", 4096, 64, 64, "auto", platform="tpu").impl == "dot"
    assert resolve("int8", 2, 64, 64, "auto", platform="tpu").impl == "dequant"


def test_measured_table_governs_only_its_backend(monkeypatch):
    """A table baked from one platform's campaign must never govern
    another platform's routing (a CPU structural campaign would pin
    dequant fleet-wide on TPU)."""
    monkeypatch.setattr(gemm_routing, "MEASURED_BACKEND", "tpu")
    monkeypatch.setattr(
        gemm_routing, "MEASURED_ROUTES",
        {("int8", 12): GemmRoute("pallas", 128, 256, 512)})
    r = resolve("int8", 4096, 64, 64, "auto", platform="tpu")
    assert r.impl == "pallas" and r.block_k == 512
    # same table consulted from CPU: backend mismatch -> analytic default
    assert resolve("int8", 4096, 64, 64, "auto", platform="cpu").impl == "dequant"
    # nearest-bucket generalization is bounded (MAX_BUCKET_DISTANCE)
    assert resolve("int8", 64, 64, 64, "auto", platform="tpu").impl == "dot"


def test_set_quant_compute_retags_without_touching_payloads():
    params, fwd = _family_forward("dit")
    q = quantize_params(params, "int8", compute="dequant")
    q2 = set_quant_compute(q, "dot")
    a = q["blocks"]["attn_q"]["kernel"]
    b = q2["blocks"]["attn_q"]["kernel"]
    assert a.compute == "dequant" and b.compute == "dot"
    assert b.payload is a.payload and b.scale is a.scale
    # "off" maps to the leaf-level "dequant"
    q3 = set_quant_compute(q2, "off")
    assert q3["blocks"]["attn_q"]["kernel"].compute == "dequant"
    with pytest.raises(ValueError, match="quant_compute"):
        set_quant_compute(q, "int8")
    # re-quantizing an already-quantized tree at the same mode re-tags too
    q4 = quantize_params(q, "int8", compute="auto")
    assert q4["blocks"]["attn_q"]["kernel"].compute == "auto"
    assert q4["blocks"]["attn_q"]["kernel"].payload is a.payload


def test_validate_quant_compute():
    for p in ("off", "auto", "dot", "pallas"):
        validate_quant_compute(p, "int8")
    validate_quant_compute("auto", "none")
    with pytest.raises(ValueError, match="quant_compute"):
        validate_quant_compute("dequant", "int8")  # leaf-level name
    with pytest.raises(ValueError, match="no quantized kernels"):
        validate_quant_compute("dot", "none")


# --------------------------------------------------------------------------
# serve identity: none / int8-storage / int8-compute are three programs
# --------------------------------------------------------------------------


def test_exec_key_distinct_none_storage_compute():
    base = ExecKey(model_id="m", scheduler="ddim", height=512, width=512,
                   steps=4, cfg=True, mesh_plan="dp1.cfg1.sp1")
    storage = dataclasses.replace(base, weight_quant="int8",
                                  quant_compute="off")
    compute = dataclasses.replace(base, weight_quant="int8",
                                  quant_compute="auto")
    forced = dataclasses.replace(base, weight_quant="int8",
                                 quant_compute="pallas")
    keys = {base, storage, compute, forced}
    assert len(keys) == 4
    tags = {k.short() for k in keys}
    assert len(tags) == 4, tags
    assert "qc-off" in storage.short()
    assert "qc-pallas" in forced.short()
    # the fleet default ("auto") needs no tag — PR-9/PR-10 rungs that set
    # weight_quant="int8" inherit the compute path without a key change
    assert "qc-" not in compute.short()
    with pytest.raises(ValueError, match="no quantized kernels"):
        dataclasses.replace(base, quant_compute="dot")


def test_pipeline_quant_compute_hook(devices8):
    from test_pipelines import build_sd_pipeline

    kw = dict(batch_size=1, do_classifier_free_guidance=False)
    pipe, _ = build_sd_pipeline(devices8, 1, weight_quant="int8", **kw)
    assert pipe.weight_report()["quant_compute"] == "auto"
    gen = lambda p: np.stack(  # noqa: E731
        p(["a cat"], num_inference_steps=1, seed=5, guidance_scale=1.0,
          output_type="np").images).astype(np.float64)
    auto = gen(pipe)  # on CPU "auto" routes dequant: storage numerics
    pipe.set_quant_compute("off")
    np.testing.assert_array_equal(gen(pipe), auto)
    # forcing the low-precision path end to end stays within the same
    # family budget the storage-only knob is pinned at (docs/PERF.md)
    pipe.set_quant_compute("dot")
    assert pipe.weight_report()["quant_compute"] == "dot"
    delta = np.abs(gen(pipe) - auto).max()
    assert delta <= 2e-2, delta
    with pytest.raises(ValueError, match="no quantized kernels"):
        build_sd_pipeline(devices8, 1, weight_quant="none",
                          quant_compute="dot", **kw)

"""Runtime overlap evidence (VERDICT r3 task 5, scheduling level).

utils/overlap.py proves the refresh collectives are *structurally*
deferrable; these tests add runtime evidence one level up: a profiler trace
of the real displaced-patch program on the 8-device mesh, run through
scripts/analyze_trace.py, shows XLA actually executing the collectives
concurrently with compute (the reference's async-NCCL behavior,
utils.py:170-190).  CPU scheduling is not TPU scheduling — the TPU-silicon
version of this number comes from the chip campaign's trace phase — but a
serializing schedule would show up here too, so the test pins a floor.
"""

import glob
import gzip
import json
import os
import sys

import jax
import jax.numpy as jnp
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"))

import analyze_trace  # noqa: E402


def test_interval_math():
    assert analyze_trace.union([(0, 10), (5, 15), (20, 30)]) == 25
    assert analyze_trace.merged([(0, 5), (3, 8), (10, 12)]) == [[0, 8], [10, 12]]
    assert analyze_trace.intersection([[0, 10]], [[5, 20]]) == 5
    assert analyze_trace.intersection([[0, 1]], [[2, 3]]) == 0


def test_analyze_synthetic_trace():
    """Two device pids; collectives half-hidden on one, fully on the other."""
    evs = [
        # device 1: fusion 0-100, all-gather 50-150 -> 50 of 100 overlapped
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1", "ts": 0, "dur": 100},
        {"ph": "X", "pid": 1, "tid": 2, "name": "all-gather-start.3", "ts": 50,
         "dur": 100},
        # device 2: fusion 0-100, collective-permute 10-60 -> fully overlapped
        {"ph": "X", "pid": 2, "tid": 1, "name": "fusion.9", "ts": 0, "dur": 100},
        {"ph": "X", "pid": 2, "tid": 2, "name": "collective-permute.2",
         "ts": 10, "dur": 50},
        # host lane: ignored (no XLA-looking names)
        {"ph": "X", "pid": 9, "tid": 9, "name": "HostPython", "ts": 0,
         "dur": 1000},
    ]
    rep = analyze_trace.analyze(evs)
    assert rep["n_devices"] == 2
    assert rep["n_collective_events"] == 2
    assert rep["collective_busy_us"] == 150.0
    assert rep["overlapped_us"] == 100.0
    assert rep["exposed_us"] == 50.0
    assert rep["collective_kinds"] == {"all-gather": 1, "collective-permute": 1}

def _tiny_patch_runner(devices8, **cfg_overrides):
    """Tiny-SDXL displaced-patch runner + its generate inputs (shared by the
    trace tests below — one place for the 8-patch geometry and the
    added-cond embed math)."""
    from distrifuser_tpu import DistriConfig
    from distrifuser_tpu.models import unet as unet_mod
    from distrifuser_tpu.parallel.runner import make_runner
    from distrifuser_tpu.schedulers import get_scheduler

    ucfg = unet_mod.tiny_config(sdxl=True)
    depth = len(ucfg.block_out_channels) - 1
    cfg = DistriConfig(devices=devices8, height=8 * 16 * (1 << depth),
                       width=128, warmup_steps=1, parallelism="patch",
                       **cfg_overrides)
    params = unet_mod.init_unet_params(jax.random.PRNGKey(0), ucfg)
    runner = make_runner(cfg, ucfg, params, get_scheduler("ddim"))
    lat = jnp.zeros((1, cfg.latent_height, cfg.latent_width, ucfg.in_channels))
    enc = jnp.zeros((2, 1, 7, ucfg.cross_attention_dim))
    emb = (ucfg.projection_class_embeddings_input_dim
           - 6 * ucfg.addition_time_embed_dim)
    added = {"text_embeds": jnp.zeros((2, 1, emb)),
             "time_ids": jnp.zeros((2, 1, 6))}

    def gen(steps):
        return runner.generate(lat, enc, guidance_scale=5.0,
                               num_inference_steps=steps, added_cond=added)

    return gen


@pytest.mark.slow
def test_comm_batch_reduces_collective_launches(devices8, tmp_path):
    """comm_batch=True must show up in the runtime trace as fewer collective
    launch events per generation (the reference's comm_checkpoint rationale,
    utils.py:181-190: bound launch overhead by batching the refresh
    exchanges).  Bitwise carry equivalence is pinned elsewhere
    (tests/test_comm_batch.py); this checks the launch-count claim itself."""
    counts = {}
    for batch in (False, True):
        gen = _tiny_patch_runner(devices8, comm_batch=batch)
        jax.block_until_ready(gen(4))
        d = tmp_path / f"trace_{batch}"
        with jax.profiler.trace(str(d), create_perfetto_trace=True):
            jax.block_until_ready(gen(4))
        rep = analyze_trace.analyze(
            analyze_trace.load_events(analyze_trace.find_perfetto(str(d))))
        counts[batch] = rep["n_collective_events"]
    assert counts[True] < counts[False], counts


@pytest.mark.slow
def test_real_runner_trace_overlap(devices8, tmp_path):
    """Trace the real displaced-patch generation (tiny SDXL config, 8-dev
    mesh) and require the analyzer to find its collectives executing
    concurrently with compute."""
    gen = _tiny_patch_runner(devices8)
    jax.block_until_ready(gen(3))  # compile outside the trace
    with jax.profiler.trace(str(tmp_path), create_perfetto_trace=True):
        jax.block_until_ready(gen(3))

    path = analyze_trace.find_perfetto(str(tmp_path))
    assert path is not None and "perfetto" in os.path.basename(path)
    rep = analyze_trace.analyze(analyze_trace.load_events(path))
    # the displaced-patch program has halo ppermutes + KV all-gathers
    assert rep["n_collective_events"] > 0, rep
    assert rep["collective_busy_us"] > 0
    # scheduling-level floor: XLA must not fully serialize the collectives
    assert rep["overlapped_frac"] is not None
    assert rep["overlapped_frac"] > 0.3, rep

"""PixArt model path: converter, caption masking, micro-conditioning,
pipeline.

The real checkpoints cannot live on this box (zero egress), so the proof
layers are: (1) numerical equivalence of the two nontrivial converter moves
(patch-embed conv -> linear, learned-sigma head slice) against torch/numpy
references; (2) a full synthetic diffusers-format state dict flowing through
convert_pixart_state_dict into a working forward; (3) exactness oracles for
the caption mask (== truncation) and the size-condition fold (== explicit
add); (4) the DistriPixArtPipeline surface end-to-end on tiny models,
including from_pretrained over a synthetic snapshot directory.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models import dit as dit_mod
from distrifuser_tpu.models import t5 as t5_mod
from distrifuser_tpu.models.vae import init_vae_params, tiny_vae_config
from distrifuser_tpu.models.weights import convert_pixart_state_dict
from distrifuser_tpu.pipelines import DistriPixArtPipeline
from distrifuser_tpu.schedulers import get_scheduler

torch = pytest.importorskip("torch")


PIXART_JSON = {
    "num_attention_heads": 4, "attention_head_dim": 16, "num_layers": 2,
    "in_channels": 4, "out_channels": 8, "patch_size": 2, "sample_size": 16,
    "caption_channels": 32,
}


def synthetic_pixart_sd(seed=0, depth=2, hidden=64, cap=32, ps=2, in_ch=4):
    """Random state dict in the diffusers PixArtTransformer2DModel layout."""
    r = np.random.RandomState(seed)
    f32 = lambda *s: (r.randn(*s) * 0.05).astype(np.float32)
    sd = {
        "pos_embed.proj.weight": f32(hidden, in_ch, ps, ps),
        "pos_embed.proj.bias": f32(hidden),
        "adaln_single.emb.timestep_embedder.linear_1.weight": f32(hidden, 256),
        "adaln_single.emb.timestep_embedder.linear_1.bias": f32(hidden),
        "adaln_single.emb.timestep_embedder.linear_2.weight": f32(hidden, hidden),
        "adaln_single.emb.timestep_embedder.linear_2.bias": f32(hidden),
        "adaln_single.linear.weight": f32(6 * hidden, hidden),
        "adaln_single.linear.bias": f32(6 * hidden),
        "caption_projection.linear_1.weight": f32(hidden, cap),
        "caption_projection.linear_1.bias": f32(hidden),
        "caption_projection.linear_2.weight": f32(hidden, hidden),
        "caption_projection.linear_2.bias": f32(hidden),
        "scale_shift_table": f32(2, hidden),
        "proj_out.weight": f32(ps * ps * 2 * in_ch, hidden),
        "proj_out.bias": f32(ps * ps * 2 * in_ch),
    }
    for i in range(depth):
        b = f"transformer_blocks.{i}"
        sd[f"{b}.scale_shift_table"] = f32(6, hidden)
        for attn in ("attn1", "attn2"):
            for proj in ("to_q", "to_k", "to_v"):
                sd[f"{b}.{attn}.{proj}.weight"] = f32(hidden, hidden)
                sd[f"{b}.{attn}.{proj}.bias"] = f32(hidden)
            sd[f"{b}.{attn}.to_out.0.weight"] = f32(hidden, hidden)
            sd[f"{b}.{attn}.to_out.0.bias"] = f32(hidden)
        sd[f"{b}.ff.net.0.proj.weight"] = f32(4 * hidden, hidden)
        sd[f"{b}.ff.net.0.proj.bias"] = f32(4 * hidden)
        sd[f"{b}.ff.net.2.weight"] = f32(hidden, 4 * hidden)
        sd[f"{b}.ff.net.2.bias"] = f32(hidden)
    return sd


def test_patch_embed_conv_equivalence():
    """Converted proj_in linear over patchify == the original strided conv."""
    sd = synthetic_pixart_sd()
    cfg = dit_mod.dit_config_from_json(PIXART_JSON)
    params = convert_pixart_state_dict(sd)
    x = np.random.RandomState(1).randn(2, 16, 16, 4).astype(np.float32)

    from distrifuser_tpu.ops.linear import linear

    ours = np.asarray(linear(params["proj_in"], dit_mod.patchify(cfg, jnp.asarray(x))))

    with torch.no_grad():
        ref = torch.nn.functional.conv2d(
            torch.tensor(x).permute(0, 3, 1, 2),
            torch.tensor(sd["pos_embed.proj.weight"]),
            torch.tensor(sd["pos_embed.proj.bias"]),
            stride=2,
        )  # [B, hidden, 8, 8]
    ref = ref.permute(0, 2, 3, 1).reshape(2, 64, 64).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5, atol=1e-5)


def test_learned_sigma_slice_equivalence():
    """Converted final_out == diffusers proj_out + unpatchify + eps slice."""
    sd = synthetic_pixart_sd()
    cfg = dit_mod.dit_config_from_json(PIXART_JSON)
    params = convert_pixart_state_dict(sd)
    h = np.random.RandomState(2).randn(1, 64, 64).astype(np.float32)

    from distrifuser_tpu.ops.linear import linear

    tokens = np.asarray(linear(params["final_out"], jnp.asarray(h)))
    ours = np.asarray(dit_mod.unpatchify(cfg, jnp.asarray(tokens), 4))

    # diffusers path: full 2C head, nhwpqc->nchpwq unpatchify, keep eps rows
    full = h @ sd["proj_out.weight"].T + sd["proj_out.bias"]  # [1, 64, 32]
    full = full.reshape(1, 8, 8, 2, 2, 8)
    ref = np.einsum("nhwpqc->nchpwq", full).reshape(1, 8, 16, 16)[:, :4]
    np.testing.assert_allclose(ours, ref.transpose(0, 2, 3, 1), rtol=1e-5, atol=1e-5)


def test_converted_forward_runs():
    sd = synthetic_pixart_sd()
    cfg = dit_mod.dit_config_from_json(PIXART_JSON)
    assert cfg.caption_dim == 32 and cfg.mlp_ratio == 4
    assert not cfg.use_additional_conditions  # sample_size 16 != 128
    params = convert_pixart_state_dict(sd)
    x = jnp.ones((1, 16, 16, 4))
    enc = jnp.ones((1, 9, 32))
    out = dit_mod.dit_forward(params, cfg, x, jnp.asarray(500.0), enc)
    assert out.shape == (1, 16, 16, 4)
    assert np.isfinite(np.asarray(out)).all()


def test_caption_mask_equals_truncation():
    """Masking padded caption tokens == feeding only the real tokens."""
    cfg = dit_mod.tiny_dit_config(depth=4)
    params = dit_mod.init_dit_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 16, 4))
    enc = jax.random.normal(jax.random.PRNGKey(2), (2, 12, cfg.caption_dim))
    mask = jnp.concatenate([jnp.ones((2, 7)), jnp.zeros((2, 5))], axis=1)
    t = jnp.asarray(300.0)

    masked = dit_mod.dit_forward(params, cfg, x, t, enc, cap_mask=mask)
    truncated = dit_mod.dit_forward(params, cfg, x, t, enc[:, :7])
    np.testing.assert_allclose(
        np.asarray(masked), np.asarray(truncated), rtol=2e-5, atol=2e-5
    )


def test_runner_caption_mask_equals_truncation():
    """The displaced runner honors cap_mask (same oracle, 4-dev mesh)."""
    from distrifuser_tpu.parallel.dit_sp import DiTDenoiseRunner

    dcfg = dit_mod.tiny_dit_config(depth=4)
    params = dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg)
    cfg = DistriConfig(
        devices=jax.devices()[:4], height=128, width=128, warmup_steps=1,
        do_classifier_free_guidance=False, split_batch=False, dtype=jnp.float32,
    )
    lat = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16, 4))
    enc = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 12, dcfg.caption_dim))
    mask = jnp.concatenate([jnp.ones((1, 1, 8)), jnp.zeros((1, 1, 4))], axis=2)

    r1 = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out_masked = r1.generate(lat, enc, guidance_scale=1.0,
                             num_inference_steps=3, cap_mask=mask)
    r2 = DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))
    out_trunc = r2.generate(lat, enc[:, :, :8], guidance_scale=1.0,
                            num_inference_steps=3)
    np.testing.assert_allclose(
        np.asarray(out_masked), np.asarray(out_trunc), rtol=2e-5, atol=2e-5
    )


def test_fold_size_condition_exact():
    """Folding the micro-conditioning into t_fc2.bias == explicit addition."""
    cfg = dit_mod.DiTConfig(
        sample_size=16, patch_size=2, hidden_size=66, depth=2, num_heads=6,
        mlp_ratio=2, caption_dim=32, use_additional_conditions=True,
    )
    params = dit_mod.init_dit_params(jax.random.PRNGKey(0), cfg)
    folded = dit_mod.fold_size_condition(params, cfg, 1024.0, 1024.0)
    t = jnp.asarray(123.0)
    explicit = dit_mod.t_embed(params, cfg, t) + dit_mod.size_condition_embed(
        params, cfg, 1024.0, 1024.0
    )
    np.testing.assert_allclose(
        np.asarray(dit_mod.t_embed(folded, cfg, t)), np.asarray(explicit),
        rtol=1e-6, atol=1e-6,
    )
    # flag off or embedders absent -> identity
    cfg_off = dit_mod.tiny_dit_config()
    p_off = dit_mod.init_dit_params(jax.random.PRNGKey(1), cfg_off)
    assert dit_mod.fold_size_condition(p_off, cfg_off, 128.0, 128.0) is p_off


def _tiny_pixart_stack(n_dev, parallelism="patch"):
    dcfg = dit_mod.tiny_dit_config(depth=4)
    t5cfg = t5_mod.tiny_t5_config()
    # caption width must match the t5 d_model for the real-encoder path
    dcfg = dit_mod.DiTConfig(
        sample_size=16, patch_size=2, hidden_size=64, depth=4, num_heads=4,
        mlp_ratio=2, caption_dim=t5cfg.d_model,
    )
    cfg = DistriConfig(
        devices=jax.devices()[:n_dev], height=128, width=128, warmup_steps=1,
        parallelism=parallelism, dtype=jnp.float32,
    )
    vcfg = tiny_vae_config()
    pipe = DistriPixArtPipeline.from_params(
        cfg, dcfg,
        dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg),
        vcfg, init_vae_params(jax.random.PRNGKey(1), vcfg),
        t5_config=t5cfg,
        t5_params=t5_mod.init_t5_params(jax.random.PRNGKey(2), t5cfg),
    )
    return pipe, cfg


@pytest.mark.parametrize("parallelism", ["patch", "pipefusion"])
def test_pixart_pipeline_generates(parallelism):
    pipe, cfg = _tiny_pixart_stack(4, parallelism)
    out = pipe(prompt="a tpu etching an image", num_inference_steps=3,
               guidance_scale=3.0, output_type="np")
    assert len(out.images) == 1
    # tiny VAE has 2 levels -> 2x upsample of the 16x16 latent
    assert out.images[0].shape == (32, 32, 3)
    assert np.isfinite(out.images[0]).all()


def test_pixart_pipeline_latent_repeatable():
    pipe, cfg = _tiny_pixart_stack(4)
    a = pipe(prompt="x", num_inference_steps=2, output_type="latent", seed=7)
    b = pipe(prompt="x", num_inference_steps=2, output_type="latent", seed=7)
    np.testing.assert_array_equal(np.asarray(a.images[0]), np.asarray(b.images[0]))


def test_pixart_from_pretrained_synthetic_snapshot(tmp_path):
    """from_pretrained over a synthetic diffusers-layout snapshot: config
    discovery, safetensors loading, conversion, and generation all engage —
    the only thing synthetic is the weight values."""
    from safetensors.numpy import save_file

    root = tmp_path / "snap"
    (root / "transformer").mkdir(parents=True)
    (root / "vae").mkdir()
    (root / "text_encoder").mkdir()
    (root / "scheduler").mkdir()

    with open(root / "transformer" / "config.json", "w") as f:
        json.dump(PIXART_JSON, f)
    save_file(synthetic_pixart_sd(),
              str(root / "transformer" / "diffusion_pytorch_model.safetensors"))

    t5cfg = t5_mod.tiny_t5_config()
    import transformers

    hf = transformers.T5EncoderModel(transformers.T5Config(
        vocab_size=t5cfg.vocab_size, d_model=t5cfg.d_model, d_kv=t5cfg.d_kv,
        d_ff=t5cfg.d_ff, num_layers=t5cfg.num_layers,
        num_heads=t5cfg.num_heads, feed_forward_proj="gated-gelu",
        dropout_rate=0.0,
    ))
    save_file({k: v.numpy() for k, v in hf.state_dict().items()},
              str(root / "text_encoder" / "model.safetensors"))
    with open(root / "text_encoder" / "config.json", "w") as f:
        json.dump({"d_model": t5cfg.d_model, "d_kv": t5cfg.d_kv,
                   "d_ff": t5cfg.d_ff, "num_layers": t5cfg.num_layers,
                   "num_heads": t5cfg.num_heads,
                   "vocab_size": t5cfg.vocab_size,
                   "feed_forward_proj": "gated-gelu"}, f)

    # VAE: dump a tiny diffusers-format state dict by inverting our param
    # tree (the same inversion the converter-roundtrip suite uses)
    from test_weights_roundtrip import invert_tree

    vcfg = tiny_vae_config()
    vparams = init_vae_params(jax.random.PRNGKey(1), vcfg)
    vsd = {}
    invert_tree(jax.tree.map(np.asarray, vparams), "", vsd)
    save_file(vsd, str(root / "vae" / "diffusion_pytorch_model.safetensors"))
    with open(root / "vae" / "config.json", "w") as f:
        json.dump({"block_out_channels": [16, 32], "layers_per_block": 1,
                   "norm_num_groups": 8, "scaling_factor": 0.18215}, f)

    cfg = DistriConfig(
        devices=jax.devices()[:4], height=128, width=128, warmup_steps=1,
        dtype=jnp.float32,
    )
    pipe = DistriPixArtPipeline.from_pretrained(cfg, str(root), scheduler="ddim")
    assert pipe.dit_config.caption_dim == t5cfg.d_model == 32
    out = pipe(prompt="snapshot smoke", num_inference_steps=2,
               output_type="latent")
    assert np.asarray(out.images[0]).shape == (16, 16, 4)
    assert np.isfinite(np.asarray(out.images[0])).all()


def test_pos_embed_interpolation_scale():
    """Coordinate scaling follows diffusers PatchEmbed: at native size the
    coords are arange/interpolation_scale, so the 1024-class table must
    equal a plain table evaluated at halved coordinates."""
    base = dit_mod.DiTConfig(sample_size=16, hidden_size=64, depth=1,
                             num_heads=4, caption_dim=32)
    scaled = dit_mod.DiTConfig(sample_size=16, hidden_size=64, depth=1,
                               num_heads=4, caption_dim=32,
                               interpolation_scale=2.0, pos_embed_base_size=8)

    t_scaled = np.asarray(dit_mod.pos_embed_table(scaled))
    # manual: coords arange(8)/(8/8)/2 = arange(8)/2
    dim = 32
    om = 1.0 / (10000.0 ** (np.arange(dim // 2) / (dim // 2)))
    coords = np.arange(8) / 2.0
    ax = np.concatenate([np.sin(coords[:, None] * om),
                         np.cos(coords[:, None] * om)], axis=-1)
    row = np.repeat(ax, 8, axis=0)
    col = np.tile(ax, (8, 1))
    np.testing.assert_allclose(t_scaled, np.concatenate([col, row], axis=-1),
                               rtol=1e-6, atol=1e-6)
    # default config unchanged (identity scaling)
    t_base = np.asarray(dit_mod.pos_embed_table(base))
    assert not np.allclose(t_base, t_scaled)

    # from_json wires the diffusers rule: 1024-class -> scale 2, base 64
    cfg = dit_mod.dit_config_from_json({"sample_size": 128})
    assert cfg.interpolation_scale == 2.0 and cfg.pos_embed_base_size == 64
    cfg512 = dit_mod.dit_config_from_json({"sample_size": 64})
    assert cfg512.interpolation_scale == 1.0


def _diffusers_2d_sincos(embed_dim, grid_size, interpolation_scale=1.0,
                         base_size=None):
    """Oracle transcribing diffusers get_2d_sincos_pos_embed structurally:
    np.meshgrid(grid_w, grid_h) puts the WIDTH coordinate in grid[0], and the
    first half of the channel dim is built from grid[0]."""
    base_size = base_size or grid_size
    coords = (np.arange(grid_size, dtype=np.float32)
              / (grid_size / base_size) / interpolation_scale)
    grid = np.stack(np.meshgrid(coords, coords), axis=0)  # [2(w,h), side, side]
    grid = grid.reshape(2, -1)

    def _1d(dim, pos):
        omega = 1.0 / 10000.0 ** (np.arange(dim // 2, dtype=np.float64)
                                  / (dim / 2.0))
        out = np.einsum("m,d->md", pos, omega)
        return np.concatenate([np.sin(out), np.cos(out)], axis=1)

    return np.concatenate(
        [_1d(embed_dim // 2, grid[0]), _1d(embed_dim // 2, grid[1])], axis=1
    )


def test_pos_embed_matches_diffusers_channel_order():
    """Column/width embedding occupies the FIRST channel half (ADVICE r3:
    row-first diagonally transposes the table for converted checkpoints).

    Pinned both against a structurally independent meshgrid oracle and
    against hardcoded sin/cos spot values, so a shared re-implementation of
    the wrong order cannot pass."""
    cfg = dit_mod.DiTConfig(sample_size=8, hidden_size=8, depth=1,
                            num_heads=2, caption_dim=8)
    table = np.asarray(dit_mod.pos_embed_table(cfg))  # [16, 8], side 4
    oracle = _diffusers_2d_sincos(8, 4)
    np.testing.assert_allclose(table, oracle, rtol=1e-6, atol=1e-6)

    # hidden 8 -> per-axis dim 4, omega = [1, 0.01]
    # token 1 = (row 0, col 1): first half encodes col=1, second half col=0
    np.testing.assert_allclose(
        table[1], [np.sin(1.0), np.sin(0.01), np.cos(1.0), np.cos(0.01),
                   0.0, 0.0, 1.0, 1.0], rtol=1e-6, atol=1e-6)
    # token 4 = (row 1, col 0): halves swap relative to token 1
    np.testing.assert_allclose(
        table[4], [0.0, 0.0, 1.0, 1.0,
                   np.sin(1.0), np.sin(0.01), np.cos(1.0), np.cos(0.01)],
        rtol=1e-6, atol=1e-6)

    # scaling path agrees with the oracle too
    cfg_s = dit_mod.DiTConfig(sample_size=8, hidden_size=8, depth=1,
                              num_heads=2, caption_dim=8,
                              interpolation_scale=2.0, pos_embed_base_size=2)
    np.testing.assert_allclose(
        np.asarray(dit_mod.pos_embed_table(cfg_s)),
        _diffusers_2d_sincos(8, 4, interpolation_scale=2.0, base_size=2),
        rtol=1e-6, atol=1e-6)


def test_pixart_pipeline_callback():
    """Pipeline-level per-step callback on the displaced-patch DiT runner
    (compiled mode); PipeFusion rejects callbacks loudly before any work."""
    pipe, cfg = _tiny_pixart_stack(4)
    seen = []
    out = pipe(prompt="a fox", num_inference_steps=3, output_type="latent",
               seed=2, callback=lambda i, t, x: seen.append((i, float(t),
                                                             x.shape)))
    assert [i for i, _, _ in seen] == [0, 1, 2]
    ts = [t for _, t, _ in seen]
    assert ts == sorted(ts, reverse=True)
    assert all(s == (1, cfg.latent_height, cfg.latent_width, 4)
               for _, _, s in seen)
    assert np.isfinite(np.asarray(out.images[0])).all()

    pipe_pf, _ = _tiny_pixart_stack(4, "pipefusion")
    with pytest.raises(ValueError, match="token"):
        pipe_pf(prompt="a fox", num_inference_steps=2, output_type="latent",
                callback=lambda i, t, x: None)


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

"""Persistent AOT executable cache (distrifuser_tpu/serve/aotcache.py):
the checksummed envelope and its typed rejections, store round-trip +
self-healing fallback, readonly/CI mode, LRU byte-budget eviction,
chaos on the load/save wire, warm-from-store replica start on fakes,
and bit-identity of cache-warm vs cold-compile on the real tiny config.
"""

import os
import struct
import tempfile

import numpy as np
import pytest

from distrifuser_tpu.serve.aotcache import (
    FORMAT_VERSION,
    MAGIC,
    AotExecutableCache,
    decode_entry,
    encode_entry,
    entry_address,
)
from distrifuser_tpu.serve.errors import AotCacheRejectedError
from distrifuser_tpu.serve.faults import FaultPlan, FaultRule
from distrifuser_tpu.serve.replica import Replica
from distrifuser_tpu.serve.testing import FakeExecutorFactory
from distrifuser_tpu.utils.aot import (
    active_aot_scope,
    aot_activation,
    runtime_fingerprint,
)
from distrifuser_tpu.utils.config import AotCacheConfig, ServeConfig


def mk_store(tmp_path, **kw):
    kw.setdefault("dir", str(tmp_path))
    return AotExecutableCache(AotCacheConfig(**kw))


def fp_for(store, scope="unet:64x64", **kw):
    return store.fingerprint(scope, **kw)


# --------------------------------------------------------------------------
# envelope: round-trip + every rejection class
# --------------------------------------------------------------------------


def test_envelope_round_trip():
    fp = {"scope": "s", "jax": "1", "jaxlib": "2", "backend": "cpu",
          "mesh_shape": "", "layout": ""}
    payload = b"program-bytes" * 100
    data = encode_entry(fp, payload)
    assert data[:4] == MAGIC
    assert decode_entry(data, fp) == payload


def test_envelope_rejects_truncation_and_corruption():
    fp = {"scope": "s", "jaxlib": "2"}
    data = encode_entry(fp, b"x" * 64)
    with pytest.raises(AotCacheRejectedError, match="truncated"):
        decode_entry(data[:8], fp)
    with pytest.raises(AotCacheRejectedError, match="checksum"):
        decode_entry(data[:-10], fp)  # digest no longer matches
    flipped = bytearray(data)
    flipped[len(data) // 2] ^= 0xFF
    with pytest.raises(AotCacheRejectedError, match="checksum"):
        decode_entry(bytes(flipped), fp)


def _resign(body: bytes) -> bytes:
    import hashlib

    return body + hashlib.sha256(body).digest()


def test_envelope_rejects_bad_magic_and_version_skew():
    fp = {"scope": "s"}
    data = encode_entry(fp, b"payload")
    body = data[:-32]
    with pytest.raises(AotCacheRejectedError, match="bad magic"):
        decode_entry(_resign(b"XXXX" + body[4:]), fp)
    # rewrite the header with a future format version and re-sign: the
    # checksum is fine, the version gate must fire
    (hlen,) = struct.unpack_from(">I", body, 4)
    import json

    meta = json.loads(body[8:8 + hlen])
    meta["format"] = FORMAT_VERSION + 1
    hdr = json.dumps(meta, sort_keys=True).encode()
    rebuilt = MAGIC + struct.pack(">I", len(hdr)) + hdr + body[8 + hlen:]
    with pytest.raises(AotCacheRejectedError, match="format version"):
        decode_entry(_resign(rebuilt), fp)


def test_envelope_rejects_fingerprint_skew():
    """A structurally intact entry whose fingerprint names a different
    jaxlib must reject, naming the differing field — version skew never
    loads a foreign program."""
    fp = {"scope": "s", "jax": "0.4.37", "jaxlib": "0.4.36"}
    data = encode_entry(fp, b"payload")
    other = dict(fp, jaxlib="0.5.0")
    with pytest.raises(AotCacheRejectedError, match="jaxlib"):
        decode_entry(data, other)


# --------------------------------------------------------------------------
# store: round-trip, self-heal, addressing
# --------------------------------------------------------------------------


def test_store_round_trip_and_miss(tmp_path):
    store = mk_store(tmp_path)
    fp = fp_for(store)
    assert store.get(fp) is None  # cold
    assert store.put(fp, b"hello world")
    assert store.get(fp) == b"hello world"
    s = store.stats()
    assert s["hits"] == 1 and s["misses"] == 1 and s["saves"] == 1
    assert s["entries"] == 1 and s["rejects"] == 0
    # a second store on the same dir adopts the entry (persistence)
    store2 = mk_store(tmp_path)
    assert store2.get(fp_for(store2)) == b"hello world"
    assert store2.stats()["hits"] == 1


def test_runtime_version_is_part_of_the_address(tmp_path):
    """Entries from a different jax/jaxlib live at different addresses:
    skew is a MISS (compile fresh), and the foreign entry survives for
    the runtime that wrote it."""
    store = mk_store(tmp_path)
    fp = fp_for(store)
    store.put(fp, b"ours")
    foreign = dict(fp, jaxlib="0.0.0-other")
    assert entry_address(foreign) != entry_address(fp)
    assert store.get(foreign) is None
    assert store.stats()["rejects"] == 0
    assert store.get(fp) == b"ours"


def test_on_disk_corruption_rejects_and_self_heals(tmp_path):
    store = mk_store(tmp_path)
    fp = fp_for(store)
    store.put(fp, b"good bytes")
    path = os.path.join(str(tmp_path), entry_address(fp) + ".aot")
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    assert store.get(fp) is None  # typed reject -> counted -> fallback
    s = store.stats()
    assert s["rejects"] == 1 and s["entries"] == 0
    assert not os.path.exists(path)  # the bad entry was deleted
    # the raw `load` raises typed (the un-counted primitive `get` wraps)
    store.put(fp, b"good bytes")
    raw2 = bytearray(open(path, "rb").read())
    raw2[-1] ^= 0xFF
    open(path, "wb").write(bytes(raw2))
    with pytest.raises(AotCacheRejectedError, match="checksum"):
        store.load(fp)


def test_renamed_entry_never_loads_as_wrong_program(tmp_path):
    """The 'never a wrong program' guarantee: a file copied onto another
    fingerprint's address fails the header fingerprint check even though
    its bytes are intact."""
    store = mk_store(tmp_path)
    fp_a = fp_for(store, scope="prog-a")
    fp_b = fp_for(store, scope="prog-b")
    store.put(fp_a, b"program-a")
    os.rename(os.path.join(str(tmp_path), entry_address(fp_a) + ".aot"),
              os.path.join(str(tmp_path), entry_address(fp_b) + ".aot"))
    store2 = mk_store(tmp_path)  # re-scan picks up the renamed file
    assert store2.get(fp_b) is None
    assert store2.stats()["rejects"] == 1


# --------------------------------------------------------------------------
# readonly mode + LRU eviction
# --------------------------------------------------------------------------


def test_readonly_store_loads_but_never_writes(tmp_path):
    writer = mk_store(tmp_path)
    fp = fp_for(writer)
    writer.put(fp, b"payload")
    ro = mk_store(tmp_path, readonly=True)
    assert ro.get(fp_for(ro)) == b"payload"  # loads serve
    assert not ro.put(fp_for(ro, scope="new"), b"nope")
    s = ro.stats()
    assert s["save_skips"] == 1 and s["saves"] == 0
    assert sorted(os.listdir(str(tmp_path))) == [
        entry_address(fp) + ".aot"]  # nothing new on disk


def test_lru_eviction_honors_byte_budget_and_recency(tmp_path):
    entry_overhead = len(encode_entry(
        fp_for(mk_store(tmp_path / "probe"), scope="s0"), b""))
    budget = 2 * (entry_overhead + 100) + 50  # room for two entries
    store = mk_store(tmp_path, max_bytes=budget)
    fps = [fp_for(store, scope=f"s{i}") for i in range(3)]
    store.put(fps[0], b"a" * 100)
    store.put(fps[1], b"b" * 100)
    store.get(fps[0])  # touch s0: s1 becomes the coldest
    store.put(fps[2], b"c" * 100)  # over budget -> evict s1
    s = store.stats()
    assert s["evictions"] == 1 and s["entries"] == 2
    assert store.get(fps[0]) is not None
    assert store.get(fps[2]) is not None
    assert store.get(fps[1]) is None  # evicted
    assert s["total_bytes"] <= budget


# --------------------------------------------------------------------------
# chaos on the wire: corrupt/truncate -> fallback to compile
# --------------------------------------------------------------------------


@pytest.mark.parametrize("site,kind", [
    ("aotcache.load", "snapshot_corrupt"),
    ("aotcache.load", "snapshot_truncate"),
    ("aotcache.save", "snapshot_corrupt"),
    ("aotcache.save", "snapshot_truncate"),
])
def test_fault_injection_falls_back_to_compile(tmp_path, site, kind):
    plan = FaultPlan([FaultRule(site=site, kind=kind, p=1.0,
                                max_fires=1)], seed=0)
    store = AotExecutableCache(AotCacheConfig(dir=str(tmp_path)),
                               fault_plan=plan)
    fp = fp_for(store)
    store.put(fp, b"the program")
    got = store.get(fp)
    assert plan.fired() == {f"{site}/{kind}": 1}
    if site == "aotcache.load":
        # intact on disk, mangled on the read: reject + self-heal
        assert got is None and store.stats()["rejects"] == 1
    else:
        # mangled on the write: the load sees a corrupt entry exactly
        # once, rejects typed, deletes it
        assert got is None and store.stats()["rejects"] == 1
    # the fallback recompiles and re-persists cleanly
    store.put(fp, b"the program")
    assert store.get(fp) == b"the program"


# --------------------------------------------------------------------------
# activation hook
# --------------------------------------------------------------------------


def test_activation_is_scoped_and_nests(tmp_path):
    store = mk_store(tmp_path)
    assert active_aot_scope() is None
    with aot_activation(store, "outer"):
        assert active_aot_scope() == (store, "outer")
        with aot_activation(store, "inner"):
            assert active_aot_scope() == (store, "inner")
        assert active_aot_scope() == (store, "outer")
    assert active_aot_scope() is None


def test_runtime_fingerprint_shape():
    fp = runtime_fingerprint()
    assert set(fp) == {"jax", "jaxlib", "backend"}
    assert all(isinstance(v, str) and v for v in fp.values())


# --------------------------------------------------------------------------
# warm-from-store replica start on fakes (the scale-up latency lever)
# --------------------------------------------------------------------------


def _replica(name, factory, store_dir):
    cfg = ServeConfig(warmup_buckets=((64, 64, 2),), default_steps=2,
                      aot_cache=AotCacheConfig(dir=store_dir))
    return Replica(name, factory, cfg)


def test_replica_warm_start_skips_the_build_delay(tmp_path):
    d = str(tmp_path)
    cold_fac = FakeExecutorFactory(build_delay_s=0.15)
    r0 = _replica("r0", cold_fac, d).start()
    try:
        cold = r0.last_warmup_s
        assert cold >= 0.15 and cold_fac.aot_warmed == 0
        assert r0.server.aot_store.stats()["saves"] >= 1
    finally:
        r0.stop()
    warm_fac = FakeExecutorFactory(build_delay_s=0.15)
    r1 = _replica("r1", warm_fac, d).start()
    try:
        warm = r1.last_warmup_s
        assert warm_fac.aot_warmed == 1  # the persisted entry was used
        assert warm < cold / 3, (
            f"warm start {warm:.3f}s not ≥3x faster than cold {cold:.3f}s"
        )
        aot = r1.server.cache.stats()["aot"]
        assert aot["hits"] >= 1 and aot["rejects"] == 0
        # the server's metrics plane exposes the store
        rendered = r1.server.registry.to_prometheus()
        assert "aot_cache_hits" in rendered
        assert "replica_warmup_s" in rendered
    finally:
        r1.stop()


def test_replica_warm_start_survives_corrupt_store(tmp_path):
    """Chaos between generations: every persisted entry corrupted on
    disk -> the next replica rejects them all (typed, counted), compiles
    fresh, and still serves."""
    d = str(tmp_path)
    r0 = _replica("r0", FakeExecutorFactory(build_delay_s=0.0), d).start()
    r0.stop()
    for name in os.listdir(d):
        path = os.path.join(d, name)
        raw = bytearray(open(path, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        open(path, "wb").write(bytes(raw))
    fac = FakeExecutorFactory(build_delay_s=0.0)
    r1 = _replica("r1", fac, d).start()
    try:
        assert fac.aot_warmed == 0
        st = r1.server.aot_store.stats()
        assert st["rejects"] >= 1
        out = r1.submit("p", height=64, width=64,
                        num_inference_steps=2).result(timeout=30)
        assert out is not None
    finally:
        r1.stop()


# --------------------------------------------------------------------------
# real tiny config: cache-warm == cold-compile, bit-identical
# --------------------------------------------------------------------------


def test_real_runner_cache_warm_is_bit_identical(tmp_path):
    """The acceptance gate: a denoise through executables deserialized
    from the store is byte-equal to the cold-compiled run that populated
    it — same config, same seeds, fresh runner."""
    import jax

    from distrifuser_tpu import DistriConfig
    from distrifuser_tpu.models.unet import init_unet_params, tiny_config
    from distrifuser_tpu.parallel.runner import DenoiseRunner
    from distrifuser_tpu.schedulers import get_scheduler
    from distrifuser_tpu.utils.compat import (
        SUPPORTS_EXECUTABLE_SERIALIZATION,
    )

    if not SUPPORTS_EXECUTABLE_SERIALIZATION:
        pytest.skip("runtime cannot serialize executables")
    store = mk_store(tmp_path)

    def run():
        cfg = DistriConfig(devices=jax.devices()[:1], height=64, width=64,
                           warmup_steps=1, mode="full_sync")
        ucfg = tiny_config()
        params = init_unet_params(jax.random.PRNGKey(0), ucfg)
        runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
        k = jax.random.PRNGKey(7)
        lat = jax.random.normal(k, (1, 8, 8, 4))
        enc = jax.random.normal(jax.random.fold_in(k, 1),
                                (2, 1, 7, ucfg.cross_attention_dim))
        with aot_activation(store, "bitident"):
            return np.asarray(
                runner.generate(lat, enc, num_inference_steps=3))

    cold = run()
    s0 = store.stats()
    assert s0["saves"] >= 1 and s0["hits"] == 0
    warm = run()
    s1 = store.stats()
    assert s1["hits"] >= 1, "second run did not load from the store"
    assert s1["deserialize_seconds"] > 0.0
    np.testing.assert_array_equal(cold, warm)

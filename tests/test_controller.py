"""Closed-loop SLO controller (serve/controller.py) + satellites: tier
table validation, deterministic load-replay dynamics on an injected clock
(escalation, hysteresis, retraction, admission), tier -> ExecKey mapping,
ladder-vs-controller precedence, typed admission rejections, the
time-aged rolling SLO windows, and the prompt/embedding cache.  All on
weightless fakes — no devices, no compiles."""

import threading
import time

import pytest

from distrifuser_tpu.serve import (
    ADMISSION,
    AdmissionRejectedError,
    ControllerConfig,
    DEFAULT_TIERS,
    ExecKey,
    InferenceServer,
    PromptCache,
    ResilienceConfig,
    RetryableError,
    SLOController,
    ServeConfig,
    TierSpec,
    apply_tier,
)
from distrifuser_tpu.serve.controller import normalize_tier_table
from distrifuser_tpu.serve.resilience import (
    RUNG_STEP_CACHE_OFF,
    ResilienceEngine,
)
from distrifuser_tpu.serve.testing import (
    FakeExecutorFactory,
    StagedFakeExecutorFactory,
)
from distrifuser_tpu.utils.metrics import MetricsRegistry, RollingQuantile


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def key_for(**kw):
    kw.setdefault("model_id", "m")
    kw.setdefault("scheduler", "ddim")
    kw.setdefault("height", 512)
    kw.setdefault("width", 512)
    kw.setdefault("steps", 4)
    kw.setdefault("cfg", True)
    kw.setdefault("mesh_plan", "dp1.cfg1.sp1")
    return ExecKey(**kw)


# ---------------------------------------------------------------------------
# tier table + key mapping
# ---------------------------------------------------------------------------


def test_tier_table_validation():
    assert normalize_tier_table(()) == DEFAULT_TIERS
    with pytest.raises(ValueError, match="cost 1.0"):
        normalize_tier_table([TierSpec("a", 0.9)])
    with pytest.raises(ValueError, match="strictly decrease"):
        normalize_tier_table([TierSpec("a", 1.0), TierSpec("b", 1.0)])
    with pytest.raises(ValueError, match="unique"):
        normalize_tier_table([TierSpec("a", 1.0), TierSpec("a", 0.5)])
    with pytest.raises(ValueError):
        TierSpec("bad", 1.0, refresh_fraction=0.3).validate()
    with pytest.raises(ValueError):
        TierSpec("bad", 1.0, step_cache=(2, 0)).validate()
    # dict entries (config-file style) normalize too
    tiers = normalize_tier_table([
        {"name": "full", "cost": 1.0},
        {"name": "cheap", "cost": 0.5, "step_cache": [2, 1]},
    ])
    assert tiers[1].step_cache == (2, 1)
    # ControllerConfig owns the lazy normalization + slo map validation
    cfg = ControllerConfig(enabled=True, slo_p99_s={"default": 1.0})
    assert cfg.tiers == DEFAULT_TIERS
    with pytest.raises(ValueError, match="default"):
        ControllerConfig(slo_p99_s={"premium": 1.0})


def test_apply_tier_key_mapping():
    base = key_for()
    assert apply_tier(base, DEFAULT_TIERS[0]) is base  # identity tier
    k = apply_tier(base, DEFAULT_TIERS[3])  # partial_refresh
    assert (k.step_cache_interval, k.step_cache_depth) == (2, 1)
    assert k.comm_compress == "int8"
    assert k.refresh_fraction == 0.5
    assert k.steps == base.steps
    k2 = apply_tier(base, DEFAULT_TIERS[4])  # reduced_steps
    assert k2.steps == 2 and k2.refresh_fraction == 0.5
    # the patch-protocol knobs never land on a pipefusion key; steps do
    pf = key_for(parallelism="pipefusion", pipe_patches=2)
    k3 = apply_tier(pf, DEFAULT_TIERS[4])
    assert k3.refresh_fraction == 1.0 and k3.comm_compress == "none"
    assert k3.steps == 2 and k3.parallelism == "pipefusion"


def test_exec_key_refresh_fraction_validation():
    k = key_for(refresh_fraction=0.5)
    assert ":pr0.5" in k.short()
    with pytest.raises(ValueError):
        key_for(refresh_fraction=0.3)
    with pytest.raises(ValueError, match="patch"):
        key_for(parallelism="pipefusion", pipe_patches=2,
                refresh_fraction=0.5)


def test_ladder_rungs_win_over_controller_tier():
    """Precedence pin: the tier maps the key FIRST, the resilience
    engine's sticky rungs apply on top — a step_cache_off rung learned on
    the tier key overrides the tier's cadence request."""
    clock = FakeClock()
    eng = ResilienceEngine(ResilienceConfig(), clock=clock)
    tier_key = apply_tier(key_for(), DEFAULT_TIERS[1])  # step_cache tier
    assert tier_key.step_cache_interval == 2
    rung = eng.degrade(tier_key, "oom", 1)
    assert rung == RUNG_STEP_CACHE_OFF
    final = eng.degraded_key(tier_key)
    assert (final.step_cache_interval, final.step_cache_depth) == (1, 0)
    # the rest of the tier's identity survives the rung
    assert final.steps == tier_key.steps


# ---------------------------------------------------------------------------
# controller dynamics: deterministic load replay on an injected clock
# ---------------------------------------------------------------------------


def _controller(clock, **cfg_kw):
    cfg_kw.setdefault("enabled", True)
    cfg_kw.setdefault("slo_p99_s", {"default": 0.5})
    cfg_kw.setdefault("escalate_cooldown_s", 1.0)
    cfg_kw.setdefault("retract_cooldown_s", 2.0)
    cfg_kw.setdefault("service_prior_s", 0.1)
    return SLOController(ControllerConfig(**cfg_kw), clock=clock,
                         batch_hint=4)


def _snap(queue=0, inflight=0, classes=None):
    return {"queue_depth": queue, "inflight_requests": inflight,
            "classes": classes or {}}


def test_escalates_under_load_one_rung_per_cooldown():
    clock = FakeClock()
    ctl = _controller(clock)
    ctl.admit("default")
    # prior 0.1s/batch, 100 queued -> predicted even at the cheapest tier
    # (cost 0.3) is 0.1*0.3*26 = 0.78 > 0.5: nothing holds, walk it all
    heavy = _snap(queue=100)
    ctl.poll(heavy)
    # class creation arms the cooldown: no move inside the first window
    assert ctl._state("default").tier == 0
    clock.advance(1.0)
    ctl.poll(heavy)
    assert ctl._state("default").tier == 1  # one rung, not a jump
    ctl.poll(heavy)
    assert ctl._state("default").tier == 1  # cooldown holds it
    for _ in range(10):
        clock.advance(1.0)
        ctl.poll(heavy)
    # walked the whole table into admission and stayed clamped there
    assert ctl._state("default").tier == len(ctl.tiers)
    assert not ctl.admit("default")


def test_retracts_when_load_drops():
    clock = FakeClock()
    ctl = _controller(clock)
    st = ctl._state("default")
    st.tier = len(ctl.tiers)  # parked at admission
    idle = _snap()
    clock.advance(5.0)
    ctl.poll(idle)
    assert st.tier == len(ctl.tiers) - 1
    for _ in range(10):
        clock.advance(5.0)
        ctl.poll(idle)
    assert st.tier == 0  # fully retracted to the identity tier
    assert ctl.admit("default")


def test_hysteresis_no_flap_at_boundary():
    """A load whose prediction sits between the retract margin and the
    target holds the tier forever: too good to escalate, not good enough
    (by margin) to retract."""
    clock = FakeClock()
    ctl = _controller(clock, retract_margin=0.5)
    st = ctl._state("default")
    st.tier = 2
    # prior 0.1, tier2 cost 0.65; load 4 batches -> predicted(tier2) =
    # 0.1*0.65*2 = 0.13 <= 0.5 (no escalation); predicted(tier1) =
    # 0.1*0.75*2 = 0.15 <= 0.5 so desired < tier... but retraction needs
    # <= margin*target = 0.25 at tier1 -- holds, 0.15 <= 0.25?  choose a
    # load where tier1 predicted lands in (0.25, 0.5): load_batches=5 ->
    # tier1 = 0.375, tier2 = 0.325 <= 0.5
    boundary = _snap(queue=20)
    transitions_before = st.transitions
    for _ in range(20):
        clock.advance(3.0)  # past every cooldown
        ctl.poll(boundary)
    assert st.tier == 2
    assert st.transitions == transitions_before


def test_measured_breach_escalates_only_under_live_load():
    clock = FakeClock()
    ctl = _controller(clock, min_samples=2)
    st = ctl._state("default")
    breach_window = {"default": {"count": 10, "window": 10, "p99": 3.0}}
    # idle: the ghost p99 from a past burst must not escalate anything
    clock.advance(2.0)
    ctl.poll(_snap(classes=breach_window))
    assert st.tier == 0
    # same window under live load: one rung down
    clock.advance(2.0)
    ctl.poll(_snap(queue=1, classes=breach_window))
    assert st.tier == 1


def test_replayed_load_is_deterministic():
    """Same clock, same snapshots -> identical tier walk (the decision is
    a pure function of its inputs)."""
    trace = [(0.0, _snap(queue=40)), (1.1, _snap(queue=40)),
             (2.2, _snap(queue=40)), (3.3, _snap(queue=2)),
             (6.0, _snap()), (9.0, _snap()), (12.0, _snap())]

    def run():
        clock = FakeClock()
        ctl = _controller(clock)
        walk = []
        for t, snap in trace:
            clock.t = t
            ctl.poll(snap)
            walk.append(ctl._state("default").tier)
        return walk

    assert run() == run()


def test_service_calibration_normalizes_by_tier_cost():
    clock = FakeClock()
    ctl = _controller(clock)
    assert ctl.service_estimate() == pytest.approx(0.1)  # the prior
    ctl.observe_batch(0, 0.2)           # full tier: 0.2 equivalent
    ctl.observe_batch(4, 0.06)          # cheapest tier (cost 0.3): 0.2 eq
    assert ctl.service_estimate() == pytest.approx(0.2)


def test_tier_for_batch_takes_cheapest_needed():
    clock = FakeClock()
    ctl = _controller(clock, slo_p99_s={"default": 0.5, "premium": 0.1})
    ctl._state("premium").tier = 3
    ctl._state("default").tier = 1
    idx, tier = ctl.tier_for_batch(["default", "premium", "default"])
    assert idx == 3 and tier is ctl.tiers[3]
    # admission-parked classes clamp to the last REAL tier for dispatch
    ctl._state("premium").tier = len(ctl.tiers)
    idx, _ = ctl.tier_for_batch(["premium"])
    assert idx == len(ctl.tiers) - 1


# ---------------------------------------------------------------------------
# server integration on fakes (real time, generous margins)
# ---------------------------------------------------------------------------


def _server(controller_kw=None, serve_kw=None, factory_kw=None):
    serve_kw = dict(serve_kw or {})
    serve_kw.setdefault("max_queue_depth", 256)
    serve_kw.setdefault("max_batch_size", 4)
    serve_kw.setdefault("batch_window_s", 0.005)
    serve_kw.setdefault("buckets", ((512, 512),))
    serve_kw.setdefault("default_steps", 4)
    serve_kw.setdefault("default_ttl_s", 10.0)
    ckw = dict(controller_kw or {})
    ckw.setdefault("enabled", True)
    ckw.setdefault("slo_p99_s", {"default": 0.2})
    ckw.setdefault("escalate_cooldown_s", 0.03)
    ckw.setdefault("retract_cooldown_s", 0.15)
    ckw.setdefault("service_prior_s", 0.08)
    config = ServeConfig(controller=ControllerConfig(**ckw), **serve_kw)
    fkw = dict(factory_kw or {})
    fkw.setdefault("batch_size", 4)
    fkw.setdefault("step_time_s", 0.02)
    factory = FakeExecutorFactory(**fkw)
    return InferenceServer(factory, config, model_id="m"), factory


def test_server_escalates_and_admission_rejects_typed():
    server, factory = _server()
    rejections = []
    with server:
        for i in range(300):
            try:
                server.submit("p", height=512, width=512, seed=i)
            except AdmissionRejectedError as exc:
                rejections.append(exc)
            except RetryableError:
                pass  # queue-full backpressure also counts as shedding
            time.sleep(0.002)
        snap = server.metrics_snapshot()
    ctl = snap["controller"]
    assert ctl["classes"]["default"]["transitions"] > 0
    # tiers actually dispatched below full quality
    disp = server.registry.counter("serve_controller_dispatches").snapshot()
    assert len(disp) > 1, disp
    # admission rejections are the typed 429 and counted
    assert rejections, "expected admission-controlled submissions"
    assert all(isinstance(e, RetryableError) for e in rejections)
    assert snap["requests"]["rejected_admission"] == len(rejections)
    # degraded tier keys hit the executor cache as distinct programs
    assert len({k.short() for k in factory.built}) > 1


def test_server_retracts_to_full_when_idle():
    server, _ = _server()
    with server:
        for i in range(200):
            try:
                server.submit("p", height=512, width=512, seed=i)
            except RetryableError:
                pass
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            snap = server.metrics_snapshot()["controller"]
            tier = snap["classes"].get("default", {}).get("tier")
            if tier == 0 and len(server.queue) == 0:
                break
            time.sleep(0.05)
        assert tier == 0, snap
    # and the walk was recorded
    trans = server.registry.counter(
        "serve_controller_transitions").snapshot()
    assert any(k.startswith("escalate:") for k in trans)
    assert any(k.startswith("retract:") for k in trans)


def test_controller_off_is_inert():
    server, factory = _server(controller_kw={"enabled": False})
    assert server.controller is None
    with server:
        server.submit("p", height=512, width=512).result(timeout=30)
        snap = server.metrics_snapshot()
    assert snap["controller"] is None
    assert all(k.refresh_fraction == 1.0 and k.step_cache_interval == 1
               for k in factory.built)


# ---------------------------------------------------------------------------
# satellite: time-aged rolling SLO windows
# ---------------------------------------------------------------------------


def test_rolling_quantile_max_age_decays():
    clock = FakeClock()
    rq = RollingQuantile(window=8, clock=clock, max_age_s=10.0)
    for v in (1.0, 2.0, 3.0):
        rq.observe(v)
    assert rq.snapshot()["window"] == 3
    assert rq.quantile(0.5) == 2.0
    clock.advance(5.0)
    rq.observe(9.0)
    clock.advance(6.0)  # first three now 11s old, the 9.0 is 6s old
    snap = rq.snapshot()
    assert snap["window"] == 1
    assert snap["p99"] == 9.0
    assert snap["count"] == 4  # lifetime total is untouched
    clock.advance(20.0)  # everything ages out
    empty = rq.snapshot()
    assert empty["window"] == 0 and "p99" not in empty
    assert empty["count"] == 4  # the lifetime total never goes backwards
    assert rq.quantile(0.99) != rq.quantile(0.99) or True  # NaN-safe read


def test_idle_server_slo_windows_decay():
    """The slo_snapshot satellite: an idle server's per-class windows
    decay instead of pinning minutes-old p99s into the controller."""
    clock = FakeClock()
    from distrifuser_tpu.serve import ObservabilityConfig

    config = ServeConfig(
        buckets=((512, 512),), max_batch_size=2,
        observability=ObservabilityConfig(slo_window=16, slo_max_age_s=30.0),
    )
    server = InferenceServer(FakeExecutorFactory(batch_size=2), config,
                             model_id="m", clock=clock)
    server.slo_window("default").observe(1.5)
    assert server.slo_snapshot()["classes"]["default"]["window"] == 1
    clock.advance(60.0)
    snap = server.slo_snapshot()["classes"]["default"]
    assert snap["window"] == 0
    assert "p99" not in snap


def test_apply_key_policy_partial_refresh_gather_only():
    """The partial direction forces only onto gather-layout builders; a
    ring/ulysses builder must fail LOUDLY instead of caching a ':pr' key
    that moves full bytes while the controller costs it as degraded."""
    import types

    from distrifuser_tpu.serve.executors import apply_key_policy

    def stub(attn_impl):
        dcfg = types.SimpleNamespace(
            parallelism="patch", attn_impl=attn_impl, refresh_fraction=1.0,
            step_cache_interval=1, step_cache_depth=0, comm_compress="none",
            weight_quant="none")
        return types.SimpleNamespace(distri_config=dcfg)

    pipe = stub("gather")
    apply_key_policy(pipe, key_for(refresh_fraction=0.5))
    assert pipe.distri_config.refresh_fraction == 0.5
    # the reset direction is always safe, any layout
    ring = stub("ring")
    ring.distri_config.refresh_fraction = 0.5
    apply_key_policy(ring, key_for())
    assert ring.distri_config.refresh_fraction == 1.0
    with pytest.raises(ValueError, match="gather layout only"):
        apply_key_policy(stub("ring"), key_for(refresh_fraction=0.5))


def test_registry_rolling_rejects_conflicting_aging():
    reg = MetricsRegistry()
    reg.rolling("w", window=8, max_age_s=10.0)
    with pytest.raises(ValueError, match="max_age_s"):
        reg.rolling("w", window=8, max_age_s=20.0)


# ---------------------------------------------------------------------------
# satellite: prompt/embedding LRU cache
# ---------------------------------------------------------------------------


def test_prompt_cache_lru_and_counters():
    cache = PromptCache(2)
    calls = []

    def enc(tag):
        def f():
            calls.append(tag)
            return {"emb": tag}
        return f

    assert cache.get_or_encode("a", enc("a")) == {"emb": "a"}
    assert cache.get_or_encode("a", enc("a2")) == {"emb": "a"}  # hit
    assert calls == ["a"]
    cache.get_or_encode("b", enc("b"))
    cache.get_or_encode("c", enc("c"))  # evicts "a" (LRU)
    assert cache.get_or_encode("a", enc("a3")) == {"emb": "a3"}
    snap = cache.snapshot()
    assert snap["entries"] == 2 and snap["capacity"] == 2
    assert snap["hits"] == 1 and snap["misses"] == 4
    assert cache.hit_rate() == pytest.approx(0.2)


def test_prompt_cache_concurrent_get_or_encode():
    cache = PromptCache(8)
    n = [0]
    lock = threading.Lock()

    def enc():
        with lock:
            n[0] += 1
        return "v"

    threads = [threading.Thread(
        target=lambda: [cache.get_or_encode("k", enc) for _ in range(50)])
        for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # racing misses may double-encode, but the value is deterministic and
    # the cache converges to one entry
    assert cache.get_or_encode("k", enc) == "v"
    assert len(cache) == 1
    assert n[0] >= 1


def test_server_prompt_cache_hits_on_repeated_prompts():
    """Staged fakes + ServeConfig.prompt_cache_capacity: repeated prompt
    chunks skip the simulated encode, the registry counter records hits,
    and outputs stay identical."""
    config = ServeConfig(
        buckets=((512, 512),), max_batch_size=2, batch_window_s=0.0,
        pipeline_stages=True, prompt_cache_capacity=8,
    )
    factory = StagedFakeExecutorFactory(batch_size=2, encode_s=0.0)
    server = InferenceServer(factory, config, model_id="m")
    with server:
        a = server.submit("same prompt", height=512, width=512,
                          seed=1).result(timeout=30)
        b = server.submit("same prompt", height=512, width=512,
                          seed=1).result(timeout=30)
        snap = server.metrics_snapshot()
    assert snap["prompt_cache"]["hits"] >= 1
    assert snap["prompt_cache"]["misses"] >= 1
    import numpy as np

    np.testing.assert_array_equal(a.output, b.output)
    counter = server.registry.counter("serve_prompt_cache").snapshot()
    assert counter.get("hits", 0) >= 1


def test_controller_counts_prompt_cache_hit_as_cheaper_input():
    clock = FakeClock()
    cache = PromptCache(4)
    cfg = ControllerConfig(enabled=True, slo_p99_s={"default": 0.5},
                           service_prior_s=0.1, encode_share=0.5)
    ctl = SLOController(cfg, clock=clock, batch_hint=4)
    ctl.prompt_cache = cache
    cache.get("k")           # miss -> hit rate 0
    assert ctl._effective_service() == pytest.approx(0.1)
    cache.put("k", 1)
    for _ in range(3):
        cache.get("k")       # hit rate 3/4
    assert ctl._effective_service() == pytest.approx(
        0.1 * (1 - 0.5 * 0.75))

"""SD3.5-medium dual attention (diffusers dual_attention_layers).

The reference predates SD3 entirely; this pins the extension's own
contracts.  Dual blocks run a SECOND image-stream-only self-attention:
its input is the same pre-attention LayerNorm of x modulated by the last
3 chunks of a 9-chunk AdaLayerNormZeroX, and its gated output lands
AFTER the joint-attention residual, BEFORE the MLP.

Oracles, strongest first:

* a LITERAL per-block reimplementation of the diffusers semantics (no
  scan, no mmdit_block) pins chunk order + residual order;
* gate-off equivalence: zeroed x_mod2 must reproduce the plain config
  bit-exactly (the dual path cannot disturb the base model);
* the displaced-patch runner against the sequential per-patch oracle
  with a second per-block KV cache for attn2;
* ring == gather, stepwise == fused, hybrid == fused (the dict-valued
  KV state threads every execution mode).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distrifuser_tpu.models import dit as dit_mod
from distrifuser_tpu.models import mmdit as mm
from distrifuser_tpu.ops.attention import sdpa
from distrifuser_tpu.ops.linear import linear
from distrifuser_tpu.parallel.mmdit_sp import MMDiTDenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.utils.config import DistriConfig

K_DUAL = 2


def make_model(qk_norm=False):
    mcfg = dataclasses.replace(
        mm.tiny_mmdit_config(), dual_attention_blocks=K_DUAL, qk_norm=qk_norm
    )
    params = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)
    # break the ones-init symmetry so the qk-norm weights actually act
    if qk_norm:
        rng = np.random.RandomState(3)

        def jitter(leaf):
            if leaf.ndim == 2:  # stacked per-depth [depth, head_dim]
                return leaf * jnp.asarray(
                    rng.rand(*leaf.shape) + 0.5, leaf.dtype
                )
            return leaf

        for name in ("x2_qnorm", "x2_knorm"):
            params["blocks_dual"][name] = jitter(params["blocks_dual"][name])
    return mcfg, params


def make_inputs(mcfg, batch=1, lc=5):
    k = jax.random.PRNGKey(7)
    lat = jax.random.normal(
        k, (batch, mcfg.sample_size, mcfg.sample_size, mcfg.in_channels)
    )
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, batch, lc, mcfg.joint_attention_dim)
    )
    pooled = jax.random.normal(
        jax.random.fold_in(k, 2), (2, batch, mcfg.pooled_projection_dim)
    )
    return lat, enc, pooled


# ---------------------------------------------------------------------------
# literal diffusers-semantics oracle (independent of mmdit_block)
# ---------------------------------------------------------------------------


def _literal_forward(params, cfg, x, t, enc, pooled):
    """Straight-line reimplementation of the dual-attention MMDiT forward
    following the published diffusers JointTransformerBlock semantics."""
    silu = jax.nn.silu

    def lin(p, h):
        out = h @ p["kernel"]
        return out + p["bias"] if "bias" in p else out

    def ln(h):
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        return (h - mu) / jnp.sqrt(var + 1e-6)

    def rms(h, w):
        b, l, c = h.shape
        d = c // cfg.num_heads
        hh = h.reshape(b, l, cfg.num_heads, d)
        y = hh / jnp.sqrt((hh * hh).mean(-1, keepdims=True) + 1e-6)
        return (y * w).reshape(b, l, c)

    def attention(q, k, v):
        b, lq, c = q.shape
        d = c // cfg.num_heads
        qh = q.reshape(b, lq, cfg.num_heads, d).transpose(0, 2, 1, 3)
        kh = k.reshape(b, k.shape[1], cfg.num_heads, d).transpose(0, 2, 1, 3)
        vh = v.reshape(b, v.shape[1], cfg.num_heads, d).transpose(0, 2, 1, 3)
        w = jax.nn.softmax(qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(d), -1)
        return (w @ vh).transpose(0, 2, 1, 3).reshape(b, lq, c)

    tokens = dit_mod.patchify(cfg, x)
    h = lin(params["proj_in"], tokens) + mm.pos_embed_cropped(cfg)[None]
    ctx = lin(params["ctx_in"], enc)
    vec = mm.cond_vec(params, cfg, t, pooled)

    for i in range(cfg.depth):
        bp = jax.tree.map(lambda l: l[i], params["blocks"])
        dual = i < cfg.dual_attention_blocks
        xm = lin(bp["x_mod"], silu(vec))
        xs1, xsc1, xg1, xs2, xsc2, xg2 = [
            c[:, None, :] for c in jnp.split(xm, 6, -1)
        ]
        cm = lin(bp["c_mod"], silu(vec))
        cs1, csc1, cg1, cs2, csc2, cg2 = [
            c[:, None, :] for c in jnp.split(cm, 6, -1)
        ]
        xln = ln(h)
        xn = xln * (1 + xsc1) + xs1
        cn = ln(ctx) * (1 + csc1) + cs1
        xq, xk, xv = jnp.split(lin(bp["x_qkv"], xn), 3, -1)
        cq, ck, cv = jnp.split(lin(bp["c_qkv"], cn), 3, -1)
        if cfg.qk_norm:
            xq, xk = rms(xq, bp["x_qnorm"]), rms(xk, bp["x_knorm"])
            cq, ck = rms(cq, bp["c_qnorm"]), rms(ck, bp["c_knorm"])
        att = attention(
            jnp.concatenate([cq, xq], 1),
            jnp.concatenate([ck, xk], 1),
            jnp.concatenate([cv, xv], 1),
        )
        lc = ctx.shape[1]
        # diffusers residual order: joint attention output first...
        h = h + xg1 * lin(bp["x_out"], att[:, lc:])
        ctx = ctx + cg1 * lin(bp["c_out"], att[:, :lc])
        if dual:
            dp = jax.tree.map(lambda l: l[i], params["blocks_dual"])
            dm = lin(dp["x_mod2"], silu(vec))
            d_s, d_sc, d_g = [c[:, None, :] for c in jnp.split(dm, 3, -1)]
            # ...then attn2 on the SAME pre-attention LayerNorm of x,
            # modulated by the LAST 3 chunks of AdaLayerNormZeroX...
            xn2 = xln * (1 + d_sc) + d_s
            q2, k2, v2 = jnp.split(lin(dp["x2_qkv"], xn2), 3, -1)
            if cfg.qk_norm:
                q2, k2 = rms(q2, dp["x2_qnorm"]), rms(k2, dp["x2_knorm"])
            h = h + d_g * lin(dp["x2_out"], attention(q2, k2, v2))
        # ...then the MLP on the UPDATED x
        xn2m = ln(h) * (1 + xsc2) + xs2
        h = h + xg2 * lin(
            bp["x_fc2"], jax.nn.gelu(lin(bp["x_fc1"], xn2m), approximate=True)
        )
        cn2m = ln(ctx) * (1 + csc2) + cs2
        ctx = ctx + cg2 * lin(
            bp["c_fc2"], jax.nn.gelu(lin(bp["c_fc1"], cn2m), approximate=True)
        )

    shift, scale = [
        c[:, None, :]
        for c in jnp.split(lin(params["final_mod"], silu(vec)), 2, -1)
    ]
    out = lin(params["final_out"], ln(h) * (1 + scale) + shift)
    return dit_mod.unpatchify(cfg, out, cfg.out_channels)


@pytest.mark.parametrize("qk_norm", [False, True])
def test_dense_matches_literal_oracle(qk_norm):
    mcfg, params = make_model(qk_norm=qk_norm)
    lat, enc, pooled = make_inputs(mcfg)
    got = mm.mmdit_forward(params, mcfg, lat, jnp.asarray(400.0), enc[0],
                           pooled[0])
    ref = _literal_forward(params, mcfg, lat, jnp.asarray(400.0), enc[0],
                           pooled[0])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_gate_off_equals_plain():
    """Zeroed dual modulation (gate2 == 0) reproduces the plain config
    bit-exactly on the shared weights — the dual path cannot perturb the
    base model."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    p_zero = dict(params)
    p_zero["blocks_dual"] = jax.tree.map(jnp.zeros_like,
                                         params["blocks_dual"])
    plain_cfg = dataclasses.replace(mcfg, dual_attention_blocks=0)
    p_plain = {k: v for k, v in params.items() if k != "blocks_dual"}
    out_z = mm.mmdit_forward(p_zero, mcfg, lat, jnp.asarray(500.0), enc[0],
                             pooled[0])
    out_p = mm.mmdit_forward(p_plain, plain_cfg, lat, jnp.asarray(500.0),
                             enc[0], pooled[0])
    np.testing.assert_array_equal(np.asarray(out_z), np.asarray(out_p))
    # and the un-zeroed dual weights actually engage
    out_d = mm.mmdit_forward(params, mcfg, lat, jnp.asarray(500.0), enc[0],
                             pooled[0])
    assert np.abs(np.asarray(out_d) - np.asarray(out_p)).max() > 0


# ---------------------------------------------------------------------------
# displaced-patch oracle with a second KV cache for attn2
# ---------------------------------------------------------------------------


def oracle_displaced_dual(params, mcfg, sched, lat, enc, pooled, gs,
                          num_steps, warmup_steps, n, do_cfg=True):
    sched.set_timesteps(num_steps)
    ts = sched.timesteps()
    x = dit_mod.patchify(mcfg, lat.astype(jnp.float32))
    batch, n_tok, _ = x.shape
    chunk = n_tok // n
    n_sync = min(warmup_steps + 1, num_steps)
    hid = mcfg.hidden_size
    k_dual = mcfg.dual_attention_blocks
    pos = mm.pos_embed_cropped(mcfg, jnp.float32)
    branches = (0, 1) if do_cfg else (0,)

    ctx0 = {br: linear(params["ctx_in"], enc[br]) for br in branches}
    zkv = lambda: (jnp.zeros((batch, n_tok, hid)),
                   jnp.zeros((batch, n_tok, hid)))
    cache = {br: [zkv() for _ in range(mcfg.depth)] for br in branches}
    cache2 = {br: [zkv() for _ in range(k_dual)] for br in branches}
    sstate = sched.init_state(x.shape)

    def run_stack(br, tokens, s, sync, offset):
        vec = mm.cond_vec(params, mcfg, ts[s], pooled[br])
        pos_rows = jax.lax.dynamic_slice_in_dim(pos, offset,
                                                tokens.shape[1], 0)
        h = linear(params["proj_in"], tokens) + pos_rows[None]
        ctx = ctx0[br]
        fresh, fresh2 = [], []

        def mk_assemble(store, l):
            def assemble(k, v):
                if sync:
                    return k, v
                ck, cv = store[br][l]
                return (
                    jax.lax.dynamic_update_slice(ck, k, (0, offset, 0)),
                    jax.lax.dynamic_update_slice(cv, v, (0, offset, 0)),
                )
            return assemble

        for l in range(mcfg.depth):
            bp = jax.tree.map(lambda a: a[l], params["blocks"])
            if l < k_dual:
                dp = jax.tree.map(lambda a: a[l], params["blocks_dual"])
                h, ctx, (k, v), (k2, v2) = mm.mmdit_block(
                    bp, mcfg, h, ctx, vec,
                    kv_assemble=mk_assemble(cache, l),
                    dual_p=dp, kv2_assemble=mk_assemble(cache2, l),
                )
                fresh2.append((k2, v2))
            else:
                h, ctx, (k, v) = mm.mmdit_block(
                    bp, mcfg, h, ctx, vec, kv_assemble=mk_assemble(cache, l)
                )
            fresh.append((k, v))
        return mm.final_layer(params, mcfg, h, vec), fresh, fresh2

    def combine(out):
        if not do_cfg:
            return out[0]
        return out[0] + gs * (out[1] - out[0])

    for s in range(num_steps):
        x_in = sched.scale_model_input(x, s)
        if s < n_sync:
            out = {}
            for br in branches:
                out[br], fr, fr2 = run_stack(br, x_in, s, True, 0)
                cache[br], cache2[br] = fr, fr2
        else:
            out = {br: [] for br in branches}
            f_all = {br: [[] for _ in range(mcfg.depth)] for br in branches}
            f2_all = {br: [[] for _ in range(k_dual)] for br in branches}
            for p in range(n):
                rows = x_in[:, p * chunk:(p + 1) * chunk]
                for br in branches:
                    e, fr, fr2 = run_stack(br, rows, s, False, p * chunk)
                    out[br].append(e)
                    for l in range(mcfg.depth):
                        f_all[br][l].append(fr[l])
                    for l in range(k_dual):
                        f2_all[br][l].append(fr2[l])
            out = {br: jnp.concatenate(v, axis=1) for br, v in out.items()}

            def cat(parts):
                return (jnp.concatenate([kv[0] for kv in parts], axis=1),
                        jnp.concatenate([kv[1] for kv in parts], axis=1))

            for br in branches:
                cache[br] = [cat(f_all[br][l]) for l in range(mcfg.depth)]
                cache2[br] = [cat(f2_all[br][l]) for l in range(k_dual)]
        x, sstate = sched.step(x, combine(out).astype(jnp.float32), s,
                               sstate)

    return dit_mod.unpatchify(mcfg, x, mcfg.out_channels)


def sp_config(n_dev, do_cfg, **kw):
    return DistriConfig(
        devices=jax.devices()[:n_dev], height=256, width=256,
        do_classifier_free_guidance=do_cfg, split_batch=do_cfg, **kw,
    )


def test_full_sync_matches_dense():
    from tests.test_mmdit_sp import dense_loop

    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, mode="full_sync")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=3)
    ref = dense_loop(params, mcfg, get_scheduler("flow-euler"), lat, enc,
                     pooled, 1.0, 3, do_cfg=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_displaced_matches_oracle():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, warmup_steps=1)
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=6)
    ref = oracle_displaced_dual(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_matches_gather():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    outs = {}
    for impl in ("gather", "ring"):
        cfg = sp_config(4, do_cfg=False, warmup_steps=1, attn_impl=impl)
        runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                    get_scheduler("flow-euler"))
        outs[impl] = np.asarray(runner.generate(
            lat, enc, pooled, guidance_scale=1.0, num_inference_steps=5
        ))
    np.testing.assert_allclose(outs["ring"], outs["gather"],
                               rtol=2e-4, atol=2e-4)


def test_stepwise_and_hybrid_match_fused():
    """The dict-valued KV state (joint + attn2) crosses the shard_map
    boundary in the stepwise layout and the hybrid handoff."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    kw = dict(guidance_scale=1.0, num_inference_steps=5)
    fused = np.asarray(
        MMDiTDenoiseRunner(sp_config(4, do_cfg=False, warmup_steps=1),
                           mcfg, params, get_scheduler("flow-euler"))
        .generate(lat, enc, pooled, **kw))
    stepwise = np.asarray(
        MMDiTDenoiseRunner(
            sp_config(4, do_cfg=False, warmup_steps=1, use_cuda_graph=False),
            mcfg, params, get_scheduler("flow-euler"))
        .generate(lat, enc, pooled, **kw))
    np.testing.assert_allclose(stepwise, fused, rtol=2e-4, atol=2e-4)
    hybrid = np.asarray(
        MMDiTDenoiseRunner(
            sp_config(4, do_cfg=False, warmup_steps=1, hybrid_loop=True),
            mcfg, params, get_scheduler("flow-euler"))
        .generate(lat, enc, pooled, **kw))
    np.testing.assert_allclose(hybrid, fused, rtol=2e-4, atol=2e-4)


def test_comm_report_counts_dual():
    mcfg, params = make_model()
    cfg = sp_config(4, do_cfg=False, warmup_steps=1)
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    rep = runner.comm_report()
    n_attn = mcfg.depth + mcfg.dual_attention_blocks
    assert rep["kv_state_elems"] == (
        n_attn * 2 * mcfg.num_tokens * mcfg.hidden_size
    )


def test_config_from_json_prefix():
    cfg = mm.mmdit_config_from_json(
        {"num_layers": 4, "num_attention_heads": 4, "attention_head_dim": 8,
         "sample_size": 32, "dual_attention_layers": [0, 1],
         "qk_norm": "rms_norm"}
    )
    assert cfg.dual_attention_blocks == 2 and cfg.qk_norm
    with pytest.raises(ValueError, match="contiguous-prefix"):
        mm.mmdit_config_from_json({"dual_attention_layers": [1, 2]})
    with pytest.raises(ValueError, match="dual_attention_blocks"):
        dataclasses.replace(mm.tiny_mmdit_config(), dual_attention_blocks=9)


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

"""Composite-block torch parity: converted weights + whole JAX blocks vs a
torch reference assembled to diffusers' semantics.

test_torch_parity.py pins the per-op ground truth; these tests pin the
*composition* — residual/norm ordering inside BasicTransformerBlock, the
time-embedding injection point of ResnetBlock2D, Transformer2DModel's
norm -> proj_in -> blocks -> proj_out -> +residual wrapper in both
projection modes — which is where a structurally-wrong port stays
shape-correct and silently ruins images (SURVEY.md §7's hard part).  The
torch side is hand-assembled from plain torch.nn modules exactly as
diffusers composes them (diffusers itself is not installed here).
"""

import numpy as np
import torch
import pytest

from distrifuser_tpu.models.unet import (
    DenseDispatch,
    basic_transformer_block,
    resnet_block,
    transformer_2d,
)
from distrifuser_tpu.models.weights import _convert, _fuse_kv

from torch_ref import (
    TorchBasicTransformerBlock,
    TorchResnetBlock2D,
    TorchTransformer2D,
)

RTOL, ATOL = 1e-4, 1e-5


def _sd(module, prefix):
    return {f"{prefix}.{k}": v.detach().numpy() for k, v in module.state_dict().items()}


def _nhwc(t):
    return np.asarray(t.permute(0, 2, 3, 1).contiguous())


def _assert_close(jax_out_nhwc, torch_out_nchw):
    np.testing.assert_allclose(
        np.moveaxis(np.asarray(jax_out_nhwc), 3, 1),
        torch_out_nchw.detach().numpy(),
        rtol=RTOL, atol=ATOL,
    )


def _randomize_norms(module):
    """Non-trivial affines so identity-affine bugs can't hide."""
    with torch.no_grad():
        for m in module.modules():
            if isinstance(m, (torch.nn.LayerNorm, torch.nn.GroupNorm)):
                m.weight.mul_(torch.randn_like(m.weight) * 0.2 + 1.0)
                m.bias.add_(torch.randn_like(m.bias) * 0.3)


@pytest.mark.parametrize("cin,cout", [(32, 32), (16, 32)])
def test_resnet_block_parity(cin, cout):
    torch.manual_seed(0)
    temb_dim, groups = 24, 8
    m = TorchResnetBlock2D(cin, cout, temb_dim, groups).eval()
    _randomize_norms(m)
    p = _convert(_sd(m, "r"))["r"]
    x = torch.randn(2, cin, 8, 12)
    temb = torch.randn(2, temb_dim)
    y_t = m(x, temb)
    y_j = resnet_block(
        DenseDispatch(), p, _nhwc(x), np.asarray(temb), "r", groups=groups
    )
    _assert_close(y_j, y_t)


def test_basic_transformer_block_parity():
    torch.manual_seed(1)
    c, heads, c_enc = 32, 4, 20
    m = TorchBasicTransformerBlock(c, heads, c_enc).eval()
    _randomize_norms(m)
    p = _fuse_kv(_convert(_sd(m, "b")))["b"]
    x = torch.randn(2, 24, c)
    enc = torch.randn(2, 7, c_enc)
    y_t = m(x, enc)
    y_j = basic_transformer_block(
        DenseDispatch(), p, np.asarray(x), np.asarray(enc), "b", heads=heads
    )
    np.testing.assert_allclose(
        np.asarray(y_j), y_t.detach().numpy(), rtol=RTOL, atol=ATOL
    )


@pytest.mark.parametrize("use_linear", [True, False])
def test_transformer_2d_parity(use_linear):
    """The full Transformer2DModel wrapper: GN(eps=1e-6) -> proj_in (linear
    or 1x1 conv, order differs vs the flatten) -> blocks -> proj_out ->
    +residual."""
    torch.manual_seed(2)
    c, heads, c_enc, groups = 32, 4, 20, 8
    m = TorchTransformer2D(c, heads, c_enc, groups, use_linear).eval()
    _randomize_norms(m)
    p = _fuse_kv(_convert(_sd(m, "t")))["t"]
    x = torch.randn(2, c, 6, 8)
    enc = torch.randn(2, 7, c_enc)
    y_t = m(x, enc)
    y_j = transformer_2d(
        DenseDispatch(), p, _nhwc(x), np.asarray(enc), "t",
        heads=heads, use_linear_projection=use_linear, norm_groups=groups,
    )
    _assert_close(y_j, y_t)

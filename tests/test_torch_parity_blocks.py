"""Composite-block torch parity: converted weights + whole JAX blocks vs a
torch reference assembled to diffusers' semantics.

test_torch_parity.py pins the per-op ground truth; these tests pin the
*composition* — residual/norm ordering inside BasicTransformerBlock, the
time-embedding injection point of ResnetBlock2D, Transformer2DModel's
norm -> proj_in -> blocks -> proj_out -> +residual wrapper in both
projection modes — which is where a structurally-wrong port stays
shape-correct and silently ruins images (SURVEY.md §7's hard part).  The
torch side is hand-assembled from plain torch.nn modules exactly as
diffusers composes them (diffusers itself is not installed here).
"""

import numpy as np
import torch
import torch.nn.functional as F
import pytest

from distrifuser_tpu.models.unet import (
    DenseDispatch,
    basic_transformer_block,
    resnet_block,
    transformer_2d,
)
from distrifuser_tpu.models.weights import _convert, _fuse_kv

RTOL, ATOL = 1e-4, 1e-5


def _sd(module, prefix):
    return {f"{prefix}.{k}": v.detach().numpy() for k, v in module.state_dict().items()}


def _nhwc(t):
    return np.asarray(t.permute(0, 2, 3, 1).contiguous())


def _assert_close(jax_out_nhwc, torch_out_nchw):
    np.testing.assert_allclose(
        np.moveaxis(np.asarray(jax_out_nhwc), 3, 1),
        torch_out_nchw.detach().numpy(),
        rtol=RTOL, atol=ATOL,
    )


class TorchAttn(torch.nn.Module):
    """diffusers Attention core: q/k/v proj, SDPA, out proj (residual lives
    in the caller, residual_connection=False there)."""

    def __init__(self, c, heads, c_enc=None, d=None):
        super().__init__()
        d = d or c // heads
        inner = heads * d
        self.heads, self.d = heads, d
        self.to_q = torch.nn.Linear(c, inner, bias=False)
        self.to_k = torch.nn.Linear(c_enc or c, inner, bias=False)
        self.to_v = torch.nn.Linear(c_enc or c, inner, bias=False)
        self.to_out = torch.nn.ModuleList([torch.nn.Linear(inner, c)])

    def forward(self, x, enc=None):
        enc = x if enc is None else enc
        b, l, _ = x.shape

        def split(t):
            return t.view(b, -1, self.heads, self.d).transpose(1, 2)

        y = F.scaled_dot_product_attention(
            split(self.to_q(x)), split(self.to_k(enc)), split(self.to_v(enc))
        )
        return self.to_out[0](y.transpose(1, 2).reshape(b, l, -1))


class TorchGEGLUFF(torch.nn.Module):
    """diffusers FeedForward with GEGLU: net.0.proj -> chunk -> a*gelu(g) -> net.2."""

    def __init__(self, c, mult=4):
        super().__init__()
        inner = c * mult
        proj = torch.nn.Linear(c, inner * 2)
        self.net = torch.nn.ModuleList(
            [torch.nn.Module(), torch.nn.Identity(), torch.nn.Linear(inner, c)]
        )
        self.net[0].proj = proj

    def forward(self, x):
        a, g = self.net[0].proj(x).chunk(2, dim=-1)
        return self.net[2](a * F.gelu(g))


class TorchBasicTransformerBlock(torch.nn.Module):
    """LN -> self-attn -> +res; LN -> cross-attn -> +res; LN -> FF -> +res."""

    def __init__(self, c, heads, c_enc):
        super().__init__()
        self.norm1 = torch.nn.LayerNorm(c)
        self.attn1 = TorchAttn(c, heads)
        self.norm2 = torch.nn.LayerNorm(c)
        self.attn2 = TorchAttn(c, heads, c_enc=c_enc)
        self.norm3 = torch.nn.LayerNorm(c)
        self.ff = TorchGEGLUFF(c)

    def forward(self, x, enc):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), enc)
        x = x + self.ff(self.norm3(x))
        return x


class TorchResnetBlock2D(torch.nn.Module):
    """GN -> silu -> conv -> +time proj -> GN -> silu -> conv -> +shortcut."""

    def __init__(self, cin, cout, temb_dim, groups):
        super().__init__()
        self.norm1 = torch.nn.GroupNorm(groups, cin)
        self.conv1 = torch.nn.Conv2d(cin, cout, 3, padding=1)
        self.time_emb_proj = torch.nn.Linear(temb_dim, cout)
        self.norm2 = torch.nn.GroupNorm(groups, cout)
        self.conv2 = torch.nn.Conv2d(cout, cout, 3, padding=1)
        if cin != cout:
            self.conv_shortcut = torch.nn.Conv2d(cin, cout, 1)

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + self.time_emb_proj(F.silu(temb))[:, :, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        if hasattr(self, "conv_shortcut"):
            x = self.conv_shortcut(x)
        return x + h


def _randomize_norms(module):
    """Non-trivial affines so identity-affine bugs can't hide."""
    with torch.no_grad():
        for m in module.modules():
            if isinstance(m, (torch.nn.LayerNorm, torch.nn.GroupNorm)):
                m.weight.mul_(torch.randn_like(m.weight) * 0.2 + 1.0)
                m.bias.add_(torch.randn_like(m.bias) * 0.3)


@pytest.mark.parametrize("cin,cout", [(32, 32), (16, 32)])
def test_resnet_block_parity(cin, cout):
    torch.manual_seed(0)
    temb_dim, groups = 24, 8
    m = TorchResnetBlock2D(cin, cout, temb_dim, groups).eval()
    _randomize_norms(m)
    p = _convert(_sd(m, "r"))["r"]
    x = torch.randn(2, cin, 8, 12)
    temb = torch.randn(2, temb_dim)
    y_t = m(x, temb)
    y_j = resnet_block(
        DenseDispatch(), p, _nhwc(x), np.asarray(temb), "r", groups=groups
    )
    _assert_close(y_j, y_t)


def test_basic_transformer_block_parity():
    torch.manual_seed(1)
    c, heads, c_enc = 32, 4, 20
    m = TorchBasicTransformerBlock(c, heads, c_enc).eval()
    _randomize_norms(m)
    p = _fuse_kv(_convert(_sd(m, "b")))["b"]
    x = torch.randn(2, 24, c)
    enc = torch.randn(2, 7, c_enc)
    y_t = m(x, enc)
    y_j = basic_transformer_block(
        DenseDispatch(), p, np.asarray(x), np.asarray(enc), "b", heads=heads
    )
    np.testing.assert_allclose(
        np.asarray(y_j), y_t.detach().numpy(), rtol=RTOL, atol=ATOL
    )


@pytest.mark.parametrize("use_linear", [True, False])
def test_transformer_2d_parity(use_linear):
    """The full Transformer2DModel wrapper: GN(eps=1e-6) -> proj_in (linear
    or 1x1 conv, order differs vs the flatten) -> blocks -> proj_out ->
    +residual."""
    torch.manual_seed(2)
    c, heads, c_enc, groups = 32, 4, 20, 8

    class TorchTransformer2D(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.norm = torch.nn.GroupNorm(groups, c, eps=1e-6)
            if use_linear:
                self.proj_in = torch.nn.Linear(c, c)
                self.proj_out = torch.nn.Linear(c, c)
            else:
                self.proj_in = torch.nn.Conv2d(c, c, 1)
                self.proj_out = torch.nn.Conv2d(c, c, 1)
            self.transformer_blocks = torch.nn.ModuleList(
                [TorchBasicTransformerBlock(c, heads, c_enc)]
            )

        def forward(self, x, enc):
            b, _, h, w = x.shape
            res = x
            hs = self.norm(x)
            if use_linear:
                hs = hs.permute(0, 2, 3, 1).reshape(b, h * w, c)
                hs = self.proj_in(hs)
            else:
                hs = self.proj_in(hs)
                hs = hs.permute(0, 2, 3, 1).reshape(b, h * w, c)
            for blk in self.transformer_blocks:
                hs = blk(hs, enc)
            if use_linear:
                hs = self.proj_out(hs)
                hs = hs.reshape(b, h, w, c).permute(0, 3, 1, 2)
            else:
                hs = hs.reshape(b, h, w, c).permute(0, 3, 1, 2)
                hs = self.proj_out(hs)
            return hs + res

    m = TorchTransformer2D().eval()
    _randomize_norms(m)
    p = _fuse_kv(_convert(_sd(m, "t")))["t"]
    x = torch.randn(2, c, 6, 8)
    enc = torch.randn(2, 7, c_enc)
    y_t = m(x, enc)
    y_j = transformer_2d(
        DenseDispatch(), p, _nhwc(x), np.asarray(enc), "t",
        heads=heads, use_linear_projection=use_linear, norm_groups=groups,
    )
    _assert_close(y_j, y_t)

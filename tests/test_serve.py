"""Serve-subsystem tests (distrifuser_tpu/serve) with the deterministic
weightless fakes — scheduler behavior only: admission, bucketing, FIFO,
deadlines, coalescing, cache eviction, metrics.  No weights, no devices;
the real-pipeline adapter is covered by test_serve_pipeline.py."""

import threading
import time

import pytest

from distrifuser_tpu.serve import (
    BucketTable,
    DeadlineExceededError,
    ExecKey,
    ExecutorCache,
    FatalError,
    InferenceServer,
    MicroBatcher,
    NoBucketError,
    QueueFullError,
    Request,
    RequestQueue,
    RetryableError,
    ServeConfig,
    ServerClosedError,
)
from distrifuser_tpu.serve.testing import FakeExecutorFactory, fake_image


def mk_request(prompt="p", h=512, w=512, steps=4, gs=5.0, seed=0,
               ttl=60.0, now=None):
    now = time.monotonic() if now is None else now
    return Request(
        prompt=prompt, height=h, width=w, num_inference_steps=steps,
        guidance_scale=gs, seed=seed, deadline=now + ttl, enqueue_ts=now,
    )


def mk_batcher(queue, table=None, **kw):
    kw.setdefault("model_id", "m")
    kw.setdefault("scheduler", "ddim")
    kw.setdefault("max_batch_size", 4)
    return MicroBatcher(queue, table or BucketTable(((512, 512), (1024, 1024))), **kw)


# --------------------------------------------------------------------------
# bucket snapping
# --------------------------------------------------------------------------


def test_bucket_snap_smallest_covering():
    table = BucketTable(((1024, 1024), (512, 512), (768, 768), (1024, 2048)))
    assert table.snap(512, 512) == (512, 512)  # exact
    assert table.snap(500, 300) == (512, 512)  # smallest covering
    assert table.snap(513, 512) == (768, 768)  # one dim over -> next bucket
    assert table.snap(600, 1200) == (1024, 2048)  # wide: skips 1024x1024
    with pytest.raises(NoBucketError):
        table.snap(4096, 4096)


def test_bucket_table_orders_by_area():
    table = BucketTable(((2048, 2048), (512, 512), (1024, 1024)))
    assert table.buckets == ((512, 512), (1024, 1024), (2048, 2048))


def test_serve_config_validates_and_sorts_buckets():
    cfg = ServeConfig(buckets=((1024, 1024), (512, 512)))
    assert cfg.buckets == ((512, 512), (1024, 1024))
    with pytest.raises(ValueError, match="multiples of 8"):
        ServeConfig(buckets=((500, 500),))
    with pytest.raises(ValueError, match="max_queue_depth"):
        ServeConfig(max_queue_depth=0)
    with pytest.raises(ValueError, match="warmup bucket"):
        ServeConfig(warmup_buckets=((512,),))


# --------------------------------------------------------------------------
# queue: bounded admission
# --------------------------------------------------------------------------


def test_queue_full_rejection():
    q = RequestQueue(max_depth=2)
    q.put(mk_request())
    q.put(mk_request())
    # typed hierarchy (serve/errors.py): a full queue is RETRYABLE (429
    # analog — try another replica), unlike a lapsed deadline
    with pytest.raises(RetryableError):
        q.put(mk_request())
    with pytest.raises(QueueFullError):
        q.put(mk_request())
    assert issubclass(DeadlineExceededError, FatalError)
    assert issubclass(NoBucketError, FatalError)
    assert issubclass(ServerClosedError, FatalError)


def test_queue_closed_rejection():
    q = RequestQueue(max_depth=2)
    q.put(mk_request())
    drained = q.close()
    assert len(drained) == 1
    with pytest.raises(ServerClosedError):
        q.put(mk_request())


# --------------------------------------------------------------------------
# batcher: FIFO, coalescing, deadlines
# --------------------------------------------------------------------------


def test_fifo_preserved_within_bucket():
    q = RequestQueue(max_depth=16)
    reqs = [mk_request(prompt=f"p{i}") for i in range(4)]
    for r in reqs:
        q.put(r)
    b = mk_batcher(q)
    key, batch = b.next_batch(timeout=0.0)
    assert [r.prompt for r in batch] == ["p0", "p1", "p2", "p3"]
    assert (key.height, key.width) == (512, 512)


def test_incompatible_requests_do_not_coalesce():
    q = RequestQueue(max_depth=16)
    q.put(mk_request(prompt="small"))
    q.put(mk_request(prompt="big", h=1000, w=1000))
    q.put(mk_request(prompt="small2"))
    q.put(mk_request(prompt="different-steps", steps=8))
    q.put(mk_request(prompt="different-scale", gs=2.0))
    b = mk_batcher(q)
    key1, batch1 = b.next_batch(timeout=0.0)
    # leader "small" coalesces with "small2" only (same bucket/steps/scale),
    # FIFO across the skipped incompatible one
    assert [r.prompt for r in batch1] == ["small", "small2"]
    key2, batch2 = b.next_batch(timeout=0.0)
    assert [r.prompt for r in batch2] == ["big"]
    assert (key2.height, key2.width) == (1024, 1024)
    _, batch3 = b.next_batch(timeout=0.0)
    assert [r.prompt for r in batch3] == ["different-steps"]
    _, batch4 = b.next_batch(timeout=0.0)
    assert [r.prompt for r in batch4] == ["different-scale"]


def test_max_batch_size_respected():
    q = RequestQueue(max_depth=16)
    for i in range(6):
        q.put(mk_request(prompt=f"p{i}"))
    b = mk_batcher(q, max_batch_size=4)
    _, batch = b.next_batch(timeout=0.0)
    assert len(batch) == 4
    _, batch2 = b.next_batch(timeout=0.0)
    assert [r.prompt for r in batch2] == ["p4", "p5"]


def test_expired_request_rejected_not_executed():
    q = RequestQueue(max_depth=16)
    dead = mk_request(prompt="late", ttl=-1.0)  # already expired
    live = mk_request(prompt="live")
    q.put(dead)
    q.put(live)
    rejected = []
    b = mk_batcher(q, on_reject=lambda r, e: rejected.append((r, e)))
    _, batch = b.next_batch(timeout=0.0)
    assert [r.prompt for r in batch] == ["live"]
    assert [r.prompt for r, _ in rejected] == ["late"]
    assert isinstance(rejected[0][1], DeadlineExceededError)
    assert not dead.future.done()  # batcher only reports; the server
    # fails the future (covered in test_server_deadline_* below)


def test_unsnappable_request_rejected():
    q = RequestQueue(max_depth=16)
    q.put(mk_request(prompt="huge", h=8192, w=8192))
    q.put(mk_request(prompt="ok"))
    rejected = []
    b = mk_batcher(q, on_reject=lambda r, e: rejected.append(e))
    _, batch = b.next_batch(timeout=0.0)
    assert [r.prompt for r in batch] == ["ok"]
    assert isinstance(rejected[0], NoBucketError)


def test_batch_window_waits_for_followers():
    q = RequestQueue(max_depth=16)
    q.put(mk_request(prompt="first"))
    b = mk_batcher(q, batch_window_s=0.5)
    late = mk_request(prompt="late-arrival")

    def arrive_late():
        time.sleep(0.1)
        q.put(late)

    t = threading.Thread(target=arrive_late)
    t.start()
    _, batch = b.next_batch(timeout=0.0)
    t.join()
    assert [r.prompt for r in batch] == ["first", "late-arrival"]


# --------------------------------------------------------------------------
# compiled-executable cache
# --------------------------------------------------------------------------


def key_for(h, w, steps=4):
    return ExecKey(model_id="m", scheduler="ddim", height=h, width=w,
                   steps=steps, cfg=True, mesh_plan="dp1.cfg1.sp1")


def test_cache_hit_miss_and_lru_eviction():
    evicted = []
    cache = ExecutorCache(
        lambda k: f"exec-{k.height}", capacity=2,
        on_evict=lambda k, e: evicted.append(k),
    )
    k1, k2, k3 = key_for(512, 512), key_for(768, 768), key_for(1024, 1024)
    assert cache.get(k1) == ("exec-512", False)
    assert cache.get(k1) == ("exec-512", True)
    assert cache.get(k2) == ("exec-768", False)
    # touch k1 so k2 is the LRU victim when k3 lands
    assert cache.get(k1)[1] is True
    assert cache.get(k3) == ("exec-1024", False)
    assert evicted == [k2]
    assert k2 not in cache and k1 in cache and k3 in cache
    # k2 rebuilds: eviction at capacity, not permanent loss
    assert cache.get(k2) == ("exec-768", False)
    s = cache.stats()
    assert s["hits"] == 2 and s["misses"] == 4 and s["evictions"] == 2


def test_cache_warmup_counts_builds():
    cache = ExecutorCache(lambda k: object(), capacity=4)
    built = cache.warmup([key_for(512, 512), key_for(768, 768),
                          key_for(512, 512)])
    assert built == 2
    assert cache.stats()["misses"] == 2


# --------------------------------------------------------------------------
# server end-to-end (fake executors)
# --------------------------------------------------------------------------


def serve_config(**kw):
    kw.setdefault("max_queue_depth", 16)
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("batch_window_s", 0.2)
    kw.setdefault("buckets", ((512, 512), (1024, 1024)))
    kw.setdefault("default_steps", 4)
    return ServeConfig(**kw)


def test_server_coalesces_concurrent_requests():
    factory = FakeExecutorFactory(batch_size=4)
    with InferenceServer(factory, serve_config()) as server:
        futs = []

        def client(i):
            futs.append(server.submit(f"p{i}", height=512, width=512, seed=i))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        results = [f.result(timeout=30) for f in futs]
    assert max(factory.batch_sizes()) >= 2  # coalescing happened
    assert {r.bucket for r in results} == {(512, 512)}
    snap = server.metrics_snapshot()
    assert snap["requests"]["completed"] == 4
    assert snap["cache"]["misses"] == 1  # one bucket -> one compile


def test_warmup_respects_guidance_mode():
    factory = FakeExecutorFactory(batch_size=4)
    config = serve_config(warmup_buckets=((512, 512, 4),), warmup_cfg=False)
    with InferenceServer(factory, config) as server:
        # a CFG-off request (guidance_scale <= 1) hits the warmed executor
        r = server.submit("p", height=512, width=512,
                          guidance_scale=1.0).result(timeout=30)
    assert r.compile_hit
    assert [k.cfg for k in factory.built] == [False]


def test_server_warmup_then_only_hits():
    factory = FakeExecutorFactory(batch_size=4)
    config = serve_config(warmup_buckets=((512, 512, 4),))
    with InferenceServer(factory, config) as server:
        assert server.cache.stats()["misses"] == 1  # the warmup build
        for i in range(3):
            r = server.submit(f"p{i}", height=512, width=512).result(timeout=30)
            assert r.compile_hit
    snap = server.metrics_snapshot()
    assert snap["cache"]["hits"] > 0
    assert snap["cache"]["misses"] == 1  # never missed on the request path
    assert snap["requests"].get("requests_compile_miss", 0) == 0


def test_fleet_mixes_patch_and_pipefusion_buckets():
    """Per-bucket strategy map (ServeConfig.bucket_parallelism): one
    fleet concurrently holds a patch-parallel and a pipeline-parallel
    executor for different resolution buckets, under distinct
    ExecKey.short() tags, and the warmup path builds the mapped keys."""
    factory = FakeExecutorFactory(batch_size=4)
    config = serve_config(
        parallelism="patch", pipe_patches=4,
        bucket_parallelism={(1024, 1024): "pipefusion"},
        warmup_buckets=((1024, 1024, 4),),
    )
    with InferenceServer(factory, config) as server:
        # warmup already built the big bucket's PIPEFUSION key
        assert factory.built[0].parallelism == "pipefusion"
        assert factory.built[0].pipe_patches == 4
        r_small = server.submit("s", height=512, width=512).result(timeout=30)
        r_big = server.submit("b", height=1024, width=1024).result(timeout=30)
        assert r_big.compile_hit  # the warmup executor served it
        stats = server.cache.stats()
    assert r_small.bucket == (512, 512) and r_big.bucket == (1024, 1024)
    assert len(stats["entries"]) == 2  # both strategies resident at once
    pf_tags = [t for t in stats["entries"] if ":pf4" in t]
    assert len(pf_tags) == 1 and "1024x1024" in pf_tags[0]
    assert all(":pf" not in t for t in stats["entries"] if "512" in t)
    built = {(k.height, k.parallelism) for k in factory.built}
    assert built == {(1024, "pipefusion"), (512, "patch")}


def test_server_deadline_rejects_queued_request():
    # occupy the single scheduler with a slow batch (4 steps x 0.1s), then
    # queue a request whose deadline lapses while it waits — it must be
    # rejected at scheduling time, never executed
    factory = FakeExecutorFactory(batch_size=4, step_time_s=0.1)
    with InferenceServer(factory, serve_config(batch_window_s=0.0)) as server:
        slow = server.submit("slow", height=512, width=512)
        time.sleep(0.1)  # scheduler picks up "slow" and blocks in execute
        fut = server.submit("too-late", height=512, width=512, ttl_s=0.05)
        slow.result(timeout=30)
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=30)
    assert server.metrics_snapshot()["requests"]["rejected_deadline"] == 1
    assert factory.batch_sizes() == [1]  # only "slow" ever executed


def test_server_result_is_deterministic_fake():
    factory = FakeExecutorFactory(batch_size=4)
    with InferenceServer(factory, serve_config()) as server:
        r = server.submit("corgi", height=512, width=512, seed=7).result(timeout=30)
    expected = fake_image("corgi", 7, factory.built[0])
    assert (r.output == expected).all()
    assert r.requested_size == (512, 512)
    assert r.e2e_s >= r.queue_wait_s >= 0


def test_server_rejects_after_stop():
    factory = FakeExecutorFactory(batch_size=4)
    server = InferenceServer(factory, serve_config()).start(warmup=False)
    server.stop()
    with pytest.raises(ServerClosedError):
        server.submit("p", height=512, width=512)


def test_wait_arrival_sleeps_through_incompatible_backlog():
    q = RequestQueue(max_depth=4)
    q.put(mk_request(prompt="incompatible"))
    seen = q.seq
    t0 = time.monotonic()
    # nothing arrives: wait_arrival must BLOCK for the window (no spin on
    # the non-empty queue) and report no change
    assert q.wait_arrival(seen, 0.1) == seen
    assert time.monotonic() - t0 >= 0.09
    q.put(mk_request(prompt="new"))
    assert q.wait_arrival(seen, 5.0) == seen + 1  # returns on arrival


def test_cancelled_future_does_not_kill_scheduler():
    factory = FakeExecutorFactory(batch_size=4, step_time_s=0.05)
    with InferenceServer(factory, serve_config()) as server:
        doomed = server.submit("cancel-me", height=512, width=512)
        doomed.cancel()  # may succeed while queued; resolution must not
        # take down the scheduler thread
        ok = server.submit("live", height=512, width=512).result(timeout=30)
    assert ok.output is not None


def test_broken_executor_fails_batch_not_server():
    class Broken:
        batch_size = 4

        def __call__(self, prompts, negs, gs, seeds):
            return []  # violates the length contract

    calls = {"n": 0}

    def factory(key):
        calls["n"] += 1
        if calls["n"] == 1:
            return Broken()
        from distrifuser_tpu.serve.testing import FakeExecutor

        return FakeExecutor(key, batch_size=4)

    config = serve_config(cache_capacity=1, batch_window_s=0.0,
                          buckets=((512, 512), (1024, 1024)))
    with InferenceServer(factory, config) as server:
        bad = server.submit("p", height=512, width=512)
        with pytest.raises(RuntimeError, match="outputs for a batch"):
            bad.result(timeout=30)
        # a different bucket evicts the broken executor (capacity 1) and
        # the server keeps serving
        ok = server.submit("p", height=1024, width=1024).result(timeout=30)
    assert ok.output is not None
    assert server.counters.get("scheduler_errors") == 1


def test_cancel_while_queued_batchmates_unaffected():
    """Cancel/deadline race (server.py _resolve): a future cancelled
    while its request is queued must stay cancelled — the scheduler's
    later set_result is swallowed — and the other requests of the SAME
    batch must complete normally."""
    factory = FakeExecutorFactory(batch_size=4, step_time_s=0.05)
    with InferenceServer(factory, serve_config(batch_window_s=0.0)) as server:
        blocker = server.submit("blocker", height=512, width=512)
        time.sleep(0.1)  # scheduler busy: the next submissions stay queued
        doomed = server.submit("doomed", height=512, width=512)
        mate = server.submit("mate", height=512, width=512)
        assert doomed.cancel()  # still queued -> cancellable
        blocker.result(timeout=30)
        r = mate.result(timeout=30)
    assert doomed.cancelled()
    with pytest.raises(Exception):  # CancelledError, never a ServeResult
        doomed.result(timeout=0)
    assert r.output is not None
    assert server.counters.get("scheduler_errors") == 0


def test_deadline_expiry_while_inflight_still_completes():
    """Deadlines gate SCHEDULING, not mesh work: a request whose deadline
    lapses after dispatch (while executing) completes normally — and the
    lateness is observable via the completed_late counter."""
    factory = FakeExecutorFactory(batch_size=4, step_time_s=0.15)  # 0.6s run
    with InferenceServer(factory, serve_config(batch_window_s=0.0)) as server:
        fut = server.submit("in-flight", height=512, width=512, ttl_s=0.3)
        r = fut.result(timeout=30)  # NOT DeadlineExceededError
    assert r.output is not None
    snap = server.metrics_snapshot()
    assert snap["requests"]["completed"] == 1
    assert snap["requests"]["completed_late"] == 1
    assert snap["requests"].get("rejected_deadline", 0) == 0


def test_stop_deterministically_fails_queued_futures():
    """stop() must resolve EVERY queued future with ServerClosedError —
    including ones the batcher pops concurrently with the stop — while
    the in-flight batch completes normally."""
    factory = FakeExecutorFactory(batch_size=4, step_time_s=0.1)  # 0.4s run
    server = InferenceServer(factory, serve_config(batch_window_s=0.0)).start()
    inflight = server.submit("in-flight", height=512, width=512)
    time.sleep(0.15)  # scheduler now executing "in-flight"
    queued = [server.submit(f"queued{i}", height=512, width=512)
              for i in range(3)]
    server.stop(timeout=10.0)
    r = inflight.result(timeout=5)  # in-flight work is never abandoned
    assert r.output is not None
    for f in queued:
        with pytest.raises(ServerClosedError):
            f.result(timeout=5)
    with pytest.raises(ServerClosedError):
        server.submit("after-stop", height=512, width=512)
    assert server.metrics_snapshot()["requests"]["rejected_server_closed"] == 3
    # idempotent: a second stop is a no-op, not an error
    server.stop(timeout=1.0)


def test_server_metrics_snapshot_schema():
    factory = FakeExecutorFactory(batch_size=4)
    with InferenceServer(factory, serve_config()) as server:
        server.submit("p", height=512, width=512).result(timeout=30)
        snap = server.metrics_snapshot()
    for section in ("config", "requests", "latency_s", "batch_size", "cache"):
        assert section in snap, section
    for hist in snap["latency_s"].values():
        assert hist["count"] == 1
        assert set(hist) >= {"mean", "min", "max", "p50", "p90", "p99"}
    import json

    json.dumps(snap)  # JSON-serializable end to end

"""CLI flag-surface parity tests (scripts/common.py).

The reference derives CFG from the flag value
(/root/reference/scripts/run_sdxl.py:87:
``do_classifier_free_guidance = guidance_scale > 1``); the config built from
argv must match, so ``--guidance_scale 1`` never builds a cfg mesh axis or
runs the unconditional branch.
"""

import argparse
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import common  # noqa: E402
from distrifuser_tpu.utils.config import CFG_AXIS  # noqa: E402


def _args(argv):
    parser = argparse.ArgumentParser()
    common.add_distri_args(parser)
    return parser.parse_args(argv)


def test_guidance_scale_1_disables_cfg(devices8):
    args = _args(["--guidance_scale", "1.0"])
    cfg = common.config_from_args(args)
    assert not cfg.do_classifier_free_guidance
    assert cfg.mesh.shape[CFG_AXIS] == 1
    # every device serves the single branch
    assert cfg.n_device_per_batch == cfg.world_size


def test_guidance_scale_default_enables_cfg(devices8):
    cfg = common.config_from_args(_args([]))
    assert cfg.do_classifier_free_guidance
    assert cfg.mesh.shape[CFG_AXIS] == 2


def test_tokenizer_fallback_is_loud(capsys):
    from distrifuser_tpu import pipelines

    tok = pipelines._tokenizer_or_fallback("/nonexistent/tokenizer/dir")
    assert isinstance(tok, pipelines.SimpleTokenizer)
    err = capsys.readouterr().err
    assert "WARNING" in err
    assert "/nonexistent/tokenizer/dir" in err


def test_img2img_flags_parse():
    args = _args(["--init_image", "in.png", "--strength", "0.5",
                  "--num_images_per_prompt", "3"])
    assert args.init_image == "in.png"
    assert args.strength == 0.5
    assert args.num_images_per_prompt == 3
    assert _args([]).init_image is None


def test_sd3_scheduler_guard_and_loader(devices8, monkeypatch):
    """sd3_example's CLI guard refuses non-flow schedulers BEFORE any model
    build; load_sd3_pipeline builds the tiny random-weight stack from the
    shared flag surface."""
    args = _args(["--random_weights", "--tiny_model",
                  "--image_size", "256", "256", "--scheduler", "flow-euler"])
    cfg = common.config_from_args(args)
    pipe = common.load_sd3_pipeline(args, cfg)
    from distrifuser_tpu.schedulers import FlowMatchEulerScheduler

    assert isinstance(pipe.scheduler, FlowMatchEulerScheduler)
    assert pipe.mmdit_config.sample_size == 32
    with pytest.raises(SystemExit, match="model_path"):
        common.load_sd3_pipeline(_args(["--scheduler", "flow-euler"]), cfg)
    # the CLI guard itself (scripts/sd3_example.py): a diffusion scheduler
    # on the flow model exits before touching any weights
    import sd3_example

    monkeypatch.setattr(sys, "argv", [
        "sd3_example.py", "--random_weights", "--tiny_model",
        "--scheduler", "ddim",
    ])
    with pytest.raises(SystemExit, match="flow-euler"):
        sd3_example.main()

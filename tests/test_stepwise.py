"""Per-step (use_cuda_graph=False parity) mode vs the fused compiled loop."""

import jax
import numpy as np
import pytest

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.parallel.runner import make_runner
from distrifuser_tpu.schedulers import get_scheduler


def build(devices, n, **kw):
    cfg = DistriConfig(devices=devices[:n], height=128, width=128,
                       warmup_steps=1, **kw)
    ucfg = tiny_config()
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    return make_runner(cfg, ucfg, params, get_scheduler("ddim")), cfg, ucfg


def inputs(cfg, ucfg):
    k = jax.random.PRNGKey(9)
    lat = jax.random.normal(k, (1, cfg.latent_height, cfg.latent_width, 4))
    n_br = 2 if cfg.do_classifier_free_guidance else 1
    enc = jax.random.normal(jax.random.fold_in(k, 1), (n_br, 1, 7, ucfg.cross_attention_dim))
    return lat, enc


@pytest.mark.parametrize("kw", [
    {},  # displaced patch, gather
    {"attn_impl": "ring"},
    {"parallelism": "naive_patch", "split_scheme": "alternate"},
    {"parallelism": "tensor"},
])
def test_stepwise_matches_fused(devices8, kw):
    fused, cfg, ucfg = build(devices8, 8, use_cuda_graph=True, **kw)
    stepw, cfg2, _ = build(devices8, 8, use_cuda_graph=False, **kw)
    lat, enc = inputs(cfg, ucfg)
    a = np.asarray(fused.generate(lat, enc, num_inference_steps=4))
    b = np.asarray(stepw.generate(lat, enc, num_inference_steps=4))
    np.testing.assert_allclose(a, b, atol=2e-4)


def test_stepwise_single_device():
    stepw, cfg, ucfg = build(jax.devices()[:1], 1, use_cuda_graph=False)
    lat, enc = inputs(cfg, ucfg)
    out = stepw.generate(lat, enc, num_inference_steps=3)
    assert np.isfinite(np.asarray(out)).all()


def test_stepwise_with_dp(devices8):
    """Per-step mode with the 3-axis mesh: state lays out over (dp,cfg,sp)."""
    stepw, cfg, ucfg = build(devices8, 8, use_cuda_graph=False,
                             dp_degree=2, batch_size=2)
    fused, _, _ = build(devices8, 8, use_cuda_graph=True,
                        dp_degree=2, batch_size=2)

    k = jax.random.PRNGKey(5)
    lat = jax.random.normal(k, (2, 16, 16, 4))
    enc = jax.random.normal(jax.random.fold_in(k, 1), (2, 2, 7, ucfg.cross_attention_dim))
    a = np.asarray(stepw.generate(lat, enc, num_inference_steps=4))
    b = np.asarray(fused.generate(lat, enc, num_inference_steps=4))
    np.testing.assert_allclose(a, b, atol=2e-4)


def test_start_step_stepwise_matches_fused(devices8):
    """img2img entry (start_step > 0): the fused loop's fori/scan offsets
    must replay the per-step schedule exactly — warmup counted from the
    first executed step."""
    fused, cfg, ucfg = build(devices8, 4, use_cuda_graph=True)
    stepw, _, _ = build(devices8, 4, use_cuda_graph=False)
    lat, enc = inputs(cfg, ucfg)
    for start in (2, 5):
        a = np.asarray(fused.generate(lat, enc, num_inference_steps=6,
                                      start_step=start))
        b = np.asarray(stepw.generate(lat, enc, num_inference_steps=6,
                                      start_step=start))
        np.testing.assert_allclose(a, b, atol=2e-4)
    # full run still differs from a tail run (the offset actually engages)
    full = np.asarray(fused.generate(lat, enc, num_inference_steps=6))
    tail = np.asarray(fused.generate(lat, enc, num_inference_steps=6,
                                     start_step=5))
    assert np.abs(full - tail).max() > 0
    with pytest.raises(AssertionError):
        fused.generate(lat, enc, num_inference_steps=4, start_step=4)


def test_stepwise_callback(devices8):
    """callback(step, timestep, latents) — the diffusers legacy signature —
    fires once per executed step from the host loop."""
    stepw, cfg, ucfg = build(devices8, 2, use_cuda_graph=False)
    lat, enc = inputs(cfg, ucfg)
    seen = []
    out = stepw.generate(
        lat, enc, num_inference_steps=4,
        callback=lambda i, t, x: seen.append((i, int(t), x.shape)))
    assert [i for i, _, _ in seen] == [0, 1, 2, 3]
    ts = [t for _, t, _ in seen]
    assert ts == sorted(ts, reverse=True) and ts[-1] >= 0  # descending sched
    assert all(s == np.asarray(out).shape for _, _, s in seen)


def test_fused_callback_matches_stepwise(devices8):
    """Callback with use_cuda_graph=True (VERDICT r4 task 4): the compiled
    loop fires the diffusers legacy callback via io_callback with the SAME
    count, order, timesteps, and latents as the host loop — in both the
    fused and hybrid configs (a callback routes hybrid through the same
    compiled-callback program)."""
    stepw, cfg, ucfg = build(devices8, 2, use_cuda_graph=False)
    fused, _, _ = build(devices8, 2, use_cuda_graph=True)
    hybrid, _, _ = build(devices8, 2, use_cuda_graph=True, hybrid_loop=True)
    lat, enc = inputs(cfg, ucfg)

    def run(runner, **kw):
        seen = []
        out = runner.generate(
            lat, enc, num_inference_steps=5,
            callback=lambda i, t, x: seen.append(
                (int(i), float(t), np.array(x, copy=True))),
            **kw,
        )
        return seen, np.asarray(out)

    s_seen, s_out = run(stepw)
    assert [i for i, _, _ in s_seen] == [0, 1, 2, 3, 4]
    for name, runner in (("fused", fused), ("hybrid", hybrid)):
        f_seen, f_out = run(runner)
        assert [i for i, _, _ in f_seen] == [i for i, _, _ in s_seen], name
        assert [t for _, t, _ in f_seen] == [t for _, t, _ in s_seen], name
        for (_, _, xa), (_, _, xb) in zip(f_seen, s_seen):
            np.testing.assert_allclose(xa, xb, atol=2e-4)
        np.testing.assert_allclose(f_out, s_out, atol=2e-4)
        # the last callback sees exactly the returned latents
        np.testing.assert_allclose(f_seen[-1][2], f_out, atol=0)

    # img2img entry: the compiled-callback loop honors start_step
    s2, _ = run(stepw, start_step=2)
    f2, _ = run(fused, start_step=2)
    assert [i for i, _, _ in f2] == [i for i, _, _ in s2] == [2, 3, 4]


def test_hybrid_matches_fused(devices8):
    """Hybrid loop (per-step sync warmup + fused stale-only scan) must equal
    the fully fused loop — it is the compile-time-resilient execution of the
    same program."""
    fused, cfg, ucfg = build(devices8, 8, use_cuda_graph=True)
    hybrid, _, _ = build(devices8, 8, use_cuda_graph=True, hybrid_loop=True)
    lat, enc = inputs(cfg, ucfg)
    a = np.asarray(fused.generate(lat, enc, num_inference_steps=5))
    b = np.asarray(hybrid.generate(lat, enc, num_inference_steps=5))
    np.testing.assert_allclose(a, b, atol=2e-4)
    # all-sync short runs take the pure stepwise path inside hybrid
    a2 = np.asarray(fused.generate(lat, enc, num_inference_steps=2))
    b2 = np.asarray(hybrid.generate(lat, enc, num_inference_steps=2))
    np.testing.assert_allclose(a2, b2, atol=2e-4)


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

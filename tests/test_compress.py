"""Quantized stale-refresh exchange (parallel/compress.py, comm_compress):
round-trip error bounds, stale-phase parity on all three model families at
pinned tolerances, warmup bit-exactness, fused-vs-stepwise equality,
carry-pytree identity across the sync/stale/shallow bodies, byte-accurate
comm accounting, the serve key surface, and (slow) the HLO proof that the
quantize/dequantize converts stay on the deferred path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models import dit as dit_mod
from distrifuser_tpu.models import mmdit as mm
from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.parallel import compress
from distrifuser_tpu.parallel.dit_sp import DiTDenoiseRunner
from distrifuser_tpu.parallel.mmdit_sp import MMDiTDenoiseRunner
from distrifuser_tpu.parallel.runner import DenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.utils.compat import shard_map

MODES = ["int8", "int8_residual"] + (["fp8"] if compress.fp8_supported()
                                     else [])


# ---------------------------------------------------------------------------
# quantizer round trips
# ---------------------------------------------------------------------------


def test_int8_roundtrip_error_bound():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 16, 64)) * 3.0
    q, s = compress.quantize(x, "int8")
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    assert s.shape == x.shape[:-1]  # one fp32 scale per tile
    back = compress.dequantize(q, s, x.dtype)
    # symmetric rounding: |err| <= scale/2 per tile, scale = amax/127
    amax = np.abs(np.asarray(x)).max(axis=-1)
    bound = amax / 127.0 / 2.0 + 1e-7
    err = np.abs(np.asarray(back) - np.asarray(x)).max(axis=-1)
    assert (err <= bound).all(), (err / amax).max()


@pytest.mark.skipif(not compress.fp8_supported(), reason="no float8_e4m3fn")
def test_fp8_roundtrip_error_bound():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16, 64)) * 3.0
    q, s = compress.quantize(x, "fp8")
    assert q.dtype == compress.fp8_dtype()
    back = np.asarray(compress.dequantize(q, s, x.dtype))
    xn = np.asarray(x)
    # e4m3 keeps ~3 mantissa bits: per-element relative error <= 2^-3 of
    # the magnitude, plus the subnormal floor near the tile scale
    amax = np.abs(xn).max(axis=-1, keepdims=True)
    bound = np.abs(xn) * 2.0**-3 + amax / 448.0
    assert (np.abs(back - xn) <= bound).all()


def test_quantize_preserves_exact_zeros():
    """Edge-device halos are exact zeros (image-border padding); the
    quantizer must keep them exact, including all-zero tiles."""
    x = jnp.zeros((2, 3, 8))
    for mode in MODES:
        q, s = compress.quantize(x, mode)
        assert not np.asarray(compress.dequantize(q, s, x.dtype)).any()
        assert np.isfinite(np.asarray(s)).all()


def test_wire_nbytes():
    # fp32 tensor, 8-wide tiles: 4 bytes/elem -> 1 byte/elem + 4/8 scale
    assert compress.wire_nbytes((2, 4, 8), 4, "none") == 256
    assert compress.wire_nbytes((2, 4, 8), 4, "int8") == 64 + 8 * 4
    assert compress.wire_nbytes((2, 4, 8), 2, "none") == 128
    # quantized wire cost is itemsize-independent (payload is 1 byte)
    assert compress.wire_nbytes((2, 4, 8), 2, "fp8") == \
        compress.wire_nbytes((2, 4, 8), 4, "int8_residual")


# ---------------------------------------------------------------------------
# config / runner validation
# ---------------------------------------------------------------------------


def test_config_validation():
    kw = dict(devices=jax.devices()[:1], height=128, width=128)
    with pytest.raises(ValueError, match="comm_compress"):
        DistriConfig(comm_compress="int4", **kw)
    with pytest.raises(ValueError, match="stale refresh"):
        DistriConfig(comm_compress="int8", parallelism="naive_patch", **kw)
    with pytest.raises(ValueError, match="stale refresh"):
        DistriConfig(comm_compress="int8", parallelism="tensor", **kw)
    # DiT: only the gather layout has a refresh collective to compress
    dcfg = dit_mod.tiny_dit_config()
    dparams = dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg)
    for impl in ("ring", "ulysses"):
        cfg = DistriConfig(devices=jax.devices()[:2],
                           height=dcfg.sample_size * 8,
                           width=dcfg.sample_size * 8, split_batch=False,
                           comm_compress="int8", attn_impl=impl)
        with pytest.raises(ValueError, match="refresh collective"):
            DiTDenoiseRunner(cfg, dcfg, dparams, get_scheduler("ddim"))
    mcfg = mm.tiny_mmdit_config()
    mparams = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)
    cfg = DistriConfig(devices=jax.devices()[:2],
                       height=mcfg.sample_size * 8,
                       width=mcfg.sample_size * 8, split_batch=False,
                       comm_compress="int8", attn_impl="ring")
    with pytest.raises(ValueError, match="refresh collective"):
        MMDiTDenoiseRunner(cfg, mcfg, mparams, get_scheduler("flow-euler"))


# ---------------------------------------------------------------------------
# UNet: parity, warmup exactness, stepwise/batched equality
# (2-device displaced meshes keep the tier-1 compile budget small; the
# 8-device variants run in the slow block)
# ---------------------------------------------------------------------------


def _unet_runner(n, **kw):
    # split_batch=False folds CFG into the batch dim, so BOTH devices of
    # the 2-dev mesh are sp peers and the refresh exchange actually exists
    # (a 2-dev cfg-split mesh is sp=1: nothing to compress)
    kw.setdefault("warmup_steps", 1)
    kw.setdefault("split_batch", False)
    cfg = DistriConfig(devices=jax.devices()[:n], height=128, width=128,
                       parallelism="patch", **kw)
    ucfg = tiny_config(sdxl=False)
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    return DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim")), cfg, ucfg


def _unet_inputs(cfg, ucfg):
    k = jax.random.PRNGKey(42)
    lat = jax.random.normal(
        k, (1, cfg.latent_height, cfg.latent_width, ucfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 1, 7, ucfg.cross_attention_dim))
    return lat, enc


# Pinned stale-parity tolerances (relative max vs the uncompressed run),
# measured on the tiny config at 4-device cfg2xsp2, 6 steps: int8 9.6e-4,
# fp8 2.9e-3, int8_residual 5.9e-4 (the closed-loop delta coder is the
# tightest, as designed).  Margins ~5-10x for platform variation; all far
# below the 0.35 displaced-mode gate in test_runner.py.
UNET_TOL = {"int8": 0.01, "fp8": 0.03, "int8_residual": 0.005}


def test_unet_stale_parity():
    """One baseline compile, every mode checked against it (a parametrized
    split would recompile the uncompressed program per case — minutes of
    tier-1 budget for no extra coverage)."""
    r_off, cfg, ucfg = _unet_runner(2)
    lat, enc = _unet_inputs(cfg, ucfg)
    a = np.asarray(r_off.generate(lat, enc, num_inference_steps=5))
    for mode in MODES:
        r_on, _, _ = _unet_runner(2, comm_compress=mode)
        b = np.asarray(r_on.generate(lat, enc, num_inference_steps=5))
        assert np.isfinite(b).all()
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
        assert rel < UNET_TOL[mode], f"{mode} drift {rel}"
        assert rel > 0, f"{mode} bit-identical: compression dead?"


def test_unet_warmup_bit_exact():
    """A run that never leaves warmup is bit-identical with compression on:
    sync exchanges never compress."""
    r_off, cfg, ucfg = _unet_runner(2, warmup_steps=4)
    r_on, _, _ = _unet_runner(2, warmup_steps=4,
                              comm_compress="int8_residual")
    lat, enc = _unet_inputs(cfg, ucfg)
    a = np.asarray(r_off.generate(lat, enc, num_inference_steps=3))
    b = np.asarray(r_on.generate(lat, enc, num_inference_steps=3))
    np.testing.assert_array_equal(a, b)


def test_unet_stepwise_and_batched_match_fused():
    """The host-driven stepwise loop and the comm_batch flat exchange must
    reproduce the fused compressed program exactly — the quantize/exchange/
    dequantize round trip is the same computation in all three."""
    r_f, cfg, ucfg = _unet_runner(2, comm_compress="int8_residual")
    r_sw, _, _ = _unet_runner(2, comm_compress="int8_residual",
                              use_cuda_graph=False)
    r_bc, _, _ = _unet_runner(2, comm_compress="int8_residual",
                              comm_batch=True)
    lat, enc = _unet_inputs(cfg, ucfg)
    a = np.asarray(r_f.generate(lat, enc, num_inference_steps=5))
    b = np.asarray(r_sw.generate(lat, enc, num_inference_steps=5))
    c = np.asarray(r_bc.generate(lat, enc, num_inference_steps=5))
    np.testing.assert_allclose(a, b, atol=2e-4)
    np.testing.assert_allclose(a, c, atol=2e-4)


@pytest.mark.slow
def test_unet_stepcache_composition():
    """Compression composes with the full/shallow cadence: finite output,
    stepwise replay equality, and the shallow phase's refresh bytes stay
    strictly below the full stale phase's.  Slow: the cadence program
    carries three step bodies (sync + full + shallow) — the most expensive
    compile in this module, and the byte assertion below also runs
    compile-free in test_bytes_report_* for tier-1."""
    kw = dict(comm_compress="int8", step_cache_interval=2,
              step_cache_depth=1)
    r_on, cfg, ucfg = _unet_runner(2, **kw)
    r_sw, _, _ = _unet_runner(2, use_cuda_graph=False, **kw)
    lat, enc = _unet_inputs(cfg, ucfg)
    a = np.asarray(r_on.generate(lat, enc, num_inference_steps=6))
    b = np.asarray(r_sw.generate(lat, enc, num_inference_steps=6))
    assert np.isfinite(a).all()
    np.testing.assert_allclose(a, b, atol=2e-4)
    rep = r_on.comm_volume_report(per_phase=True)
    assert sum(rep["bytes"]["shallow"].values()) < sum(
        rep["bytes"]["stale"].values())


# ---------------------------------------------------------------------------
# carry-pytree identity across sync / stale / shallow bodies
# ---------------------------------------------------------------------------


def _state_struct(runner, step, pstate_in):
    """eval_shape one step body's emitted patch state through the same
    shard_map harness the comm report uses."""
    cfg = runner.cfg
    runner.scheduler.set_timesteps(4)
    lat, enc, added, gs = runner._abstract_inputs(per_group=True)
    has_state = pstate_in is not None

    def one_step(params, latents, enc, added, gs, *maybe_state):
        my_enc, my_added, _ = runner._branch_inputs(enc, added)
        from distrifuser_tpu.models.unet import precompute_text_kv

        text_kv = precompute_text_kv(params, my_enc)
        sstate = runner.scheduler.init_state(latents.shape)
        _, pout, _ = step(
            params, 1, latents.astype(jnp.float32),
            maybe_state[0] if has_state else None, sstate,
            my_enc, my_added, text_kv, gs,
        )
        return pout

    args = (runner.params, lat, enc, added, gs)
    specs = (runner.param_specs, P(), P(), P(), P())
    if has_state:
        args += (pstate_in,)
        specs += (P(),)
    return jax.eval_shape(
        lambda *a: shard_map(one_step, mesh=cfg.mesh, in_specs=specs,
                             out_specs=P(), check_vma=False)(*a),
        *args,
    )


@pytest.mark.parametrize("mode", ["int8", "int8_residual"])
def test_carry_pytree_identity(mode):
    """The sync-seeded carry must be structurally identical (names, shapes,
    dtypes) to what the stale and shallow bodies return — a lax.scan carry
    cannot change structure, and residual mode's own-rows entries must be
    present in every phase."""
    from distrifuser_tpu.parallel.context import OWN_SUFFIX
    from distrifuser_tpu.parallel.runner import PHASE_STALE, PHASE_SYNC

    r, _, _ = _unet_runner(2, comm_compress=mode, step_cache_interval=2,
                           step_cache_depth=1)
    sync = _state_struct(r, r._make_step(PHASE_SYNC), None)
    stale = _state_struct(r, r._make_step(PHASE_STALE), sync)
    shallow = _state_struct(r, r._make_step(PHASE_STALE, shallow=True), sync)

    def desc(tree):
        return {k: (v.shape, str(v.dtype)) for k, v in tree.items()}

    assert desc(sync) == desc(stale) == desc(shallow)
    has_own = any(k.endswith(OWN_SUFFIX) for k in sync)
    assert has_own == (mode == "int8_residual")


# ---------------------------------------------------------------------------
# byte-accurate comm accounting (eval_shape only: no compiles, so the
# acceptance-criterion mesh runs in tier-1)
# ---------------------------------------------------------------------------


def test_bytes_report_int8_reduction(devices8):
    """Acceptance: >= 1.9x stale-phase refresh BYTE reduction at int8 on
    the tiny config, with warmup/sync traffic byte-identical to "none"."""
    def rep(mode):
        cfg = DistriConfig(devices=devices8, height=128, width=128,
                           warmup_steps=1, parallelism="patch",
                           comm_compress=mode)
        ucfg = tiny_config(sdxl=False)
        params = init_unet_params(jax.random.PRNGKey(0), ucfg)
        r = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
        return r.comm_volume_report(per_phase=True)

    off, on = rep("none"), rep("int8")
    assert off["bytes"]["sync"] == on["bytes"]["sync"]
    # element counts are mode-independent (the carry stays full precision)
    assert off["phases"] == on["phases"]
    s_off = sum(off["bytes"]["stale"].values())
    s_on = sum(on["bytes"]["stale"].values())
    assert s_off / s_on >= 1.9, (off["bytes"]["stale"], on["bytes"]["stale"])
    # the compressed kinds individually shrink; gn stays full precision
    for kind in ("attn", "conv2d"):
        assert on["bytes"]["stale"][kind] < off["bytes"]["stale"][kind]
    assert on["bytes"]["stale"]["gn"] == off["bytes"]["stale"]["gn"]


def test_bytes_report_shallow_below_stale(devices8):
    """Step-cache composition, compile-free half: under the cadence the
    shallow phase's fresh refresh bytes stay strictly below the full stale
    phase's (the numeric-equality half runs in the slow
    test_unet_stepcache_composition)."""
    cfg = DistriConfig(devices=devices8, height=128, width=128,
                       warmup_steps=1, parallelism="patch",
                       comm_compress="int8", step_cache_interval=2,
                       step_cache_depth=1)
    ucfg = tiny_config(sdxl=False)
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    r = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    rep = r.comm_volume_report(per_phase=True)
    assert sum(rep["bytes"]["shallow"].values()) < sum(
        rep["bytes"]["stale"].values())


def test_bytes_report_residual_own_rows_are_wire_free(devices8):
    cfg = DistriConfig(devices=devices8, height=128, width=128,
                       warmup_steps=1, parallelism="patch",
                       comm_compress="int8_residual")
    ucfg = tiny_config(sdxl=False)
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    r = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    rep = r.comm_volume_report(per_phase=True)
    # own-rows ride the carry (elements > 0) but never the wire (bytes == 0)
    assert rep["phases"]["stale"].get("local", 0) > 0
    assert rep["bytes"]["stale"].get("local", 1) == 0
    assert rep["bytes"]["sync"].get("local", 1) == 0


def test_dit_mmdit_closed_form_bytes():
    dcfg = dit_mod.tiny_dit_config()
    dparams = dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg)

    def dit_rep(mode):
        cfg = DistriConfig(devices=jax.devices()[:2],
                           height=dcfg.sample_size * 8,
                           width=dcfg.sample_size * 8, split_batch=False,
                           comm_compress=mode)
        return DiTDenoiseRunner(cfg, dcfg, dparams,
                                get_scheduler("ddim")).comm_report()

    off, on = dit_rep("none"), dit_rep("int8")
    assert on["sync_step_collective_bytes"] == off["sync_step_collective_bytes"]
    assert off["per_step_collective_bytes"] / on["per_step_collective_bytes"] \
        >= 1.9
    mcfg = mm.tiny_mmdit_config()
    mparams = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)

    def mm_rep(mode):
        cfg = DistriConfig(devices=jax.devices()[:2],
                           height=mcfg.sample_size * 8,
                           width=mcfg.sample_size * 8, split_batch=False,
                           comm_compress=mode)
        return MMDiTDenoiseRunner(cfg, mcfg, mparams,
                                  get_scheduler("flow-euler")).comm_report()

    off, on = mm_rep("none"), mm_rep("int8_residual")
    assert off["per_step_collective_bytes"] / on["per_step_collective_bytes"] \
        >= 1.9


def test_phase_step_counts():
    from distrifuser_tpu.parallel.stepcache import phase_step_counts

    assert phase_step_counts(10, 1, 1) == {"sync": 2, "stale": 8,
                                           "shallow": 0}
    assert phase_step_counts(10, 1, 2) == {"sync": 2, "stale": 4,
                                           "shallow": 4}
    assert phase_step_counts(2, 4, 2) == {"sync": 2, "stale": 0,
                                          "shallow": 0}
    assert phase_step_counts(0, 1, 2) == {"sync": 0, "stale": 0,
                                          "shallow": 0}


# ---------------------------------------------------------------------------
# DiT / MMDiT stale parity
# ---------------------------------------------------------------------------


# Measured at 4-device, 6 steps: DiT int8 1.1e-5 / fp8 5.4e-5 / residual
# 2.3e-6; MMDiT int8 1.9e-5 / residual 2.2e-6.  The transformer KV payload
# is far less error-sensitive than the UNet's halo rows (attention softmax
# averages the perturbation); pin at ~20x margin.
DIT_TOL = {"int8": 1e-3, "fp8": 2e-3, "int8_residual": 5e-4}


def test_dit_stale_parity():
    dcfg = dit_mod.tiny_dit_config()
    params = dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg)
    k = jax.random.PRNGKey(3)
    lat = jax.random.normal(
        k, (1, dcfg.sample_size, dcfg.sample_size, dcfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 1, 8, dcfg.caption_dim))

    def mk(**kw):
        cfg = DistriConfig(devices=jax.devices()[:2],
                           height=dcfg.sample_size * 8,
                           width=dcfg.sample_size * 8, warmup_steps=1,
                           split_batch=False, **kw)
        return DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))

    a = np.asarray(mk().generate(lat, enc, num_inference_steps=5))
    for mode in ("int8", "int8_residual"):
        b = np.asarray(mk(comm_compress=mode).generate(
            lat, enc, num_inference_steps=5))
        assert np.isfinite(b).all()
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
        assert 0 < rel < DIT_TOL[mode], f"DiT {mode} drift {rel}"


def test_mmdit_stale_parity():
    mcfg = mm.tiny_mmdit_config()
    params = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)
    k = jax.random.PRNGKey(7)
    lat = jax.random.normal(
        k, (1, mcfg.sample_size, mcfg.sample_size, mcfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 1, 5, mcfg.joint_attention_dim))
    pooled = jax.random.normal(
        jax.random.fold_in(k, 2), (2, 1, mcfg.pooled_projection_dim))

    def mk(**kw):
        cfg = DistriConfig(devices=jax.devices()[:2],
                           height=mcfg.sample_size * 8,
                           width=mcfg.sample_size * 8, warmup_steps=1,
                           split_batch=False, **kw)
        return MMDiTDenoiseRunner(cfg, mcfg, params,
                                  get_scheduler("flow-euler"))

    a = np.asarray(mk().generate(lat, enc, pooled, num_inference_steps=5))
    b = np.asarray(mk(comm_compress="int8_residual").generate(
        lat, enc, pooled, num_inference_steps=5))
    assert np.isfinite(b).all()
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    assert 0 < rel < DIT_TOL["int8_residual"], f"MMDiT drift {rel}"


# ---------------------------------------------------------------------------
# serve surfaces
# ---------------------------------------------------------------------------


def test_serve_exec_key_comm_compress():
    from distrifuser_tpu.serve.cache import ExecKey
    from distrifuser_tpu.utils.config import ServeConfig

    base = dict(model_id="m", scheduler="ddim", height=512, width=512,
                steps=8, cfg=True, mesh_plan="dp1.cfg1.sp1")
    k_off = ExecKey(**base)
    k_on = ExecKey(**base, comm_compress="int8")
    # two requests differing only in compression must not share an executor
    assert k_off != k_on
    assert ":int8" in k_on.short() and ":int8" not in k_off.short()
    with pytest.raises(ValueError, match="comm_compress"):
        ExecKey(**base, comm_compress="lz4")
    with pytest.raises(ValueError, match="comm_compress"):
        ServeConfig(comm_compress="lz4")
    cfg = ServeConfig(comm_compress="int8_residual")
    assert cfg.comm_compress == "int8_residual"


def test_serve_server_threads_comm_compress():
    from distrifuser_tpu.serve.server import InferenceServer
    from distrifuser_tpu.serve.testing import FakeExecutorFactory
    from distrifuser_tpu.utils.config import ServeConfig

    cfg = ServeConfig(comm_compress="int8", warmup_buckets=((512, 512, 4),))
    srv = InferenceServer(FakeExecutorFactory(batch_size=2), cfg,
                          model_id="m")
    keys = srv._warmup_keys()
    assert keys and all(k.comm_compress == "int8" for k in keys)


def test_apply_key_policy_forces_compress_off():
    from distrifuser_tpu.serve.cache import ExecKey
    from distrifuser_tpu.serve.executors import apply_key_policy

    class _Pipe:
        def __init__(self, dcfg):
            self.distri_config = dcfg

    dcfg = DistriConfig(devices=jax.devices()[:1], height=128, width=128,
                        comm_compress="int8")
    pipe = _Pipe(dcfg)
    key = ExecKey(model_id="m", scheduler="ddim", height=128, width=128,
                  steps=4, cfg=True, mesh_plan="dp1.cfg1.sp1")
    apply_key_policy(pipe, key)
    assert dcfg.comm_compress == "none"


def test_comm_plan_raises_without_byte_model():
    """A runner with no byte-modeled comm report must make comm_plan
    RAISE, not hand back a confident-looking empty plan (the PipeFusion
    carve-out used to return total_bytes=None silently; every first-party
    runner now carries a byte model, so reaching the fallback is a bug in
    the runner, not a condition to paper over)."""
    import types

    from distrifuser_tpu.pipelines import _GenerationMixin

    class Shell(_GenerationMixin):
        def __init__(self):
            self.distri_config = types.SimpleNamespace(
                comm_compress="none", warmup_steps=1,
                step_cache_interval=1, step_cache_depth=0,
                step_cache_enabled=False)
            self.runner = object()  # neither comm_volume_report nor comm_report

    with pytest.raises(ValueError, match="byte-model"):
        Shell().comm_plan(4)


def test_pipeline_comm_plan(devices8):
    from test_pipelines import build_sd_pipeline

    pipe, _ = build_sd_pipeline(devices8, 2, comm_compress="int8",
                                warmup_steps=1, split_batch=False)
    plan = pipe.comm_plan(6)
    assert plan["comm_compress"] == "int8"
    assert plan["steps"] == {"sync": 2, "stale": 4, "shallow": 0}
    assert plan["bytes_per_step"]["stale"] < plan["bytes_per_step"]["sync"]
    assert plan["total_bytes"] == (
        2 * plan["bytes_per_step"]["sync"] + 4 * plan["bytes_per_step"]["stale"]
    )


# ---------------------------------------------------------------------------
# HLO: the quantize/dequantize converts stay on the deferred path
# (8-device compiles: minutes on the tier-1 CPU runner -> slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_hlo_compressed_refresh_stays_deferred(devices8):
    """The compressed stale body must keep every refresh collective off the
    inline (serializing) path: payload + scale exchanges classify deferred
    or deferred_compute (carry-only through the dequantize's elementwise
    convert/multiply/add chain, utils/overlap.py elementwise_carry), the
    inline set stays exactly the uncompressed program's (the per-step
    output gather), and the collective COUNT doubles (payload + scale per
    refresh) — proof the scales ride their own exchange rather than
    widening the payload."""
    from distrifuser_tpu.models import unet as unet_mod
    from distrifuser_tpu.utils.overlap import analyze_loop_collectives

    ucfg = unet_mod.tiny_config(sdxl=False)
    params = unet_mod.init_unet_params(jax.random.PRNGKey(0), ucfg)
    depth = len(ucfg.block_out_channels) - 1

    def hlo(**kw):
        cfg = DistriConfig(
            devices=devices8, height=8 * 8 * (1 << depth) * 2, width=128,
            warmup_steps=1, parallelism="patch", mode="separate_gn", **kw,
        )
        runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
        lat = jnp.zeros(
            (1, cfg.latent_height, cfg.latent_width, ucfg.in_channels))
        enc = jnp.zeros((2, 1, 7, ucfg.cross_attention_dim))
        fn = runner._build(6)
        return fn.lower(params, lat, enc, None, 5.0).compile().as_text()

    def pick_stale(reports):
        assert reports, "no while-loop collectives found"
        return max(reports, key=lambda r: r.n_deferred + r.n_deferred_compute)

    def count(rep, prefix, *buckets):
        return sum(1 for b in buckets
                   for op in getattr(rep, b).values() if op.startswith(prefix))

    off = pick_stale(analyze_loop_collectives(hlo(), elementwise_carry=True))
    on = pick_stale(analyze_loop_collectives(
        hlo(comm_compress="int8_residual"), elementwise_carry=True))

    # nothing new serializes: the inline opcode multiset is unchanged
    assert sorted(on.inline.values()) == sorted(off.inline.values()), (
        on.inline, off.inline)
    # the dequantize chains exist and classify deferred-compute, not inline
    assert on.n_deferred_compute > 0, (on.deferred, on.inline)
    # in the uncompressed body the refresh collectives are exactly the
    # pure-data-movement `deferred` set; compressed, each becomes a payload
    # + scale PAIR riding the dequant chain (deferred_compute), while any
    # carry-only-through-arithmetic collective the baseline already had
    # (off.deferred_compute) is not refresh traffic and stays single
    for prefix in ("all-gather", "collective-permute"):
        n_refresh_off = count(off, prefix, "deferred")
        n_other_off = count(off, prefix, "deferred_compute")
        n_on = count(on, prefix, "deferred", "deferred_compute")
        assert n_refresh_off > 0 or prefix == "all-gather", prefix
        assert n_on == 2 * n_refresh_off + n_other_off, (
            prefix, n_on, n_refresh_off, n_other_off)


@pytest.mark.slow
def test_unet_multi_device_parity_8dev(devices8):
    """Displaced 8-device (cfg 2 x sp 4) parity at the pinned tolerances,
    all modes, against the uncompressed run."""
    r_off, cfg, ucfg = _unet_runner(8)
    lat, enc = _unet_inputs(cfg, ucfg)
    a = np.asarray(r_off.generate(lat, enc, num_inference_steps=6))
    for mode in MODES:
        r_on, _, _ = _unet_runner(8, comm_compress=mode)
        b = np.asarray(r_on.generate(lat, enc, num_inference_steps=6))
        assert np.isfinite(b).all()
        rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
        assert 0 < rel < UNET_TOL[mode], f"{mode} 8-dev drift {rel}"


@pytest.mark.slow
def test_residual_drift_does_not_accumulate():
    """Closed-loop DPCM regression: the int8_residual delta is taken
    against the RECONSTRUCTED previous value on both the gather path
    (stale-buffer slot) and the halo path (own-rows predictor carry,
    context._halo_record) — so per-step quantization errors cancel
    instead of random-walking.  A 24-step run (22 stale) must drift no
    more than a handful of times the 6-step run; the open-loop bug this
    pins (raw rows as predictor) grew linearly with step count."""
    r_off, cfg, ucfg = _unet_runner(4)
    r_res, _, _ = _unet_runner(4, comm_compress="int8_residual")
    lat, enc = _unet_inputs(cfg, ucfg)

    def drift(steps):
        a = np.asarray(r_off.generate(lat, enc, num_inference_steps=steps))
        b = np.asarray(r_res.generate(lat, enc, num_inference_steps=steps))
        return np.abs(a - b).max() / (np.abs(a).max() + 1e-6)

    d6, d24 = drift(6), drift(24)
    # measured: 4.4e-4 at 6 steps, 3.3e-4 at 24 — flat.  3x leaves noise
    # margin while an accumulating coder (~4x more stale steps) fails.
    assert d24 < 3 * d6 + 1e-5, (d6, d24)

"""Scheduler property tests.

With an oracle model that predicts the *true* noise, each sampler must follow
the exact diffusion trajectory: from x_t = a_t x0 + s_t n the step must land
on x_{t_prev} = a_prev x0 + s_prev n (DDIM / DPM++), or the sigma-space
equivalent for Euler.  This pins the coefficient tables without needing
diffusers on the box.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distrifuser_tpu.schedulers import (
    DDIMScheduler,
    DPMSolverMultistepScheduler,
    EulerDiscreteScheduler,
    get_scheduler,
)


def test_factory_and_timesteps_leading_spacing():
    s = get_scheduler("ddim").set_timesteps(50)
    ts = np.asarray(s.timesteps())
    # diffusers leading spacing, 1000 train steps, offset 1: 981, 961, ..., 1
    assert ts[0] == 981 and ts[1] == 961 and ts[-1] == 1
    assert len(ts) == 50
    with pytest.raises(ValueError):
        get_scheduler("plms")


def test_ddim_exact_trajectory():
    s = DDIMScheduler().set_timesteps(20)
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, (1, 4, 4, 2))
    n = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    a = np.asarray(s._alpha_t)
    ap = np.asarray(s._alpha_prev)
    state = s.init_state(x0.shape)
    for i in range(20):
        x_t = np.sqrt(a[i]) * x0 + np.sqrt(1 - a[i]) * n
        x_prev, state = s.step(jnp.asarray(x_t), n, i, state)
        want = np.sqrt(ap[i]) * x0 + np.sqrt(1 - ap[i]) * n
        np.testing.assert_allclose(np.asarray(x_prev), np.asarray(want), atol=1e-5)


def test_euler_exact_trajectory():
    s = EulerDiscreteScheduler().set_timesteps(20)
    key = jax.random.PRNGKey(2)
    x0 = jax.random.normal(key, (1, 4, 4, 2))
    n = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    sig = np.asarray(s._sigmas)
    state = {}
    for i in range(20):
        x_t = x0 + sig[i] * n  # sigma-space latent
        # model sees the descaled input; with epsilon oracle output = n
        scaled = s.scale_model_input(jnp.asarray(x_t), i)
        assert np.isfinite(np.asarray(scaled)).all()
        x_next, state = s.step(jnp.asarray(x_t), n, i, state)
        want = x0 + sig[i + 1] * n
        np.testing.assert_allclose(np.asarray(x_next), np.asarray(want), atol=1e-4)
    # last sigma is 0: trajectory ends at x0
    np.testing.assert_allclose(np.asarray(x_next), np.asarray(x0), atol=1e-4)


def test_euler_init_noise_sigma_large():
    s = EulerDiscreteScheduler().set_timesteps(30)
    # leading spacing starts at t=981 where sigma ~ 11.5 (t=999 would be ~157)
    assert 10 < s.init_noise_sigma < 13


def test_dpmsolver_exact_trajectory():
    s = DPMSolverMultistepScheduler().set_timesteps(20)
    key = jax.random.PRNGKey(3)
    x0 = jax.random.normal(key, (1, 4, 4, 2))
    n = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    a = np.asarray(s._alpha)
    sg = np.asarray(s._sigma)
    state = s.init_state(x0.shape)
    x = a[0] * x0 + sg[0] * n
    for i in range(20):
        # oracle epsilon at the current point of the exact trajectory
        eps = (np.asarray(x) - a[i] * np.asarray(x0)) / max(sg[i], 1e-12)
        x, state = s.step(jnp.asarray(x), jnp.asarray(eps), i, state)
        want = a[i + 1] * x0 + sg[i + 1] * n
        np.testing.assert_allclose(np.asarray(x), np.asarray(want), atol=1e-3)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x0), atol=1e-3)


def test_v_prediction_exact_trajectory():
    """SD 2.x parity: with a true-v oracle (v = a*n - s*x0) every sampler must
    follow the same exact trajectory as the epsilon case."""
    import numpy as np

    key = jax.random.PRNGKey(5)
    x0 = jax.random.normal(key, (1, 4, 4, 2))
    n = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)

    s = DDIMScheduler(prediction_type="v_prediction").set_timesteps(15)
    a, ap = np.asarray(s._alpha_t), np.asarray(s._alpha_prev)
    state = s.init_state(x0.shape)
    for i in range(15):
        x_t = np.sqrt(a[i]) * x0 + np.sqrt(1 - a[i]) * n
        v = np.sqrt(a[i]) * np.asarray(n) - np.sqrt(1 - a[i]) * np.asarray(x0)
        x_prev, state = s.step(jnp.asarray(x_t), jnp.asarray(v), i, state)
        want = np.sqrt(ap[i]) * x0 + np.sqrt(1 - ap[i]) * n
        np.testing.assert_allclose(np.asarray(x_prev), np.asarray(want), atol=1e-4)

    e = EulerDiscreteScheduler(prediction_type="v_prediction").set_timesteps(15)
    sig = np.asarray(e._sigmas)
    for i in range(15):
        x_t = x0 + sig[i] * n  # sigma space
        ac = 1.0 / (sig[i] ** 2 + 1.0)
        v = np.sqrt(ac) * np.asarray(n) - np.sqrt(1 - ac) * np.asarray(x0)
        x_next, _ = e.step(jnp.asarray(x_t), jnp.asarray(v), i, {})
        want = x0 + sig[i + 1] * n
        np.testing.assert_allclose(np.asarray(x_next), np.asarray(want), atol=1e-4)

    # DPM: pins the a_t**2 alpha-cumprod argument (self._alpha stores sqrt)
    dpm = DPMSolverMultistepScheduler(prediction_type="v_prediction").set_timesteps(15)
    a, sg = np.asarray(dpm._alpha), np.asarray(dpm._sigma)
    state = dpm.init_state(x0.shape)
    x = a[0] * np.asarray(x0) + sg[0] * np.asarray(n)
    for i in range(15):
        eps = (x - a[i] * np.asarray(x0)) / max(sg[i], 1e-12)
        v = a[i] * eps - sg[i] * np.asarray(x0)
        x, state = dpm.step(jnp.asarray(x), jnp.asarray(v), i, state)
        x = np.asarray(x)
        want = a[i + 1] * np.asarray(x0) + sg[i + 1] * np.asarray(n)
        np.testing.assert_allclose(x, want, atol=1e-3)


def test_steps_inside_scan():
    """Schedulers must compose with lax.scan (static shapes, traced indices)."""
    for name in ["ddim", "euler", "dpm-solver"]:
        s = get_scheduler(name).set_timesteps(10)
        x = jnp.ones((1, 2, 2, 1)) * s.init_noise_sigma
        state = s.init_state(x.shape)

        def body(carry, i):
            x, st = carry
            eps = jnp.zeros_like(x)
            x, st = s.step(x, eps, i, st)
            return (x, st), None

        (xf, _), _ = jax.jit(
            lambda x0, st: jax.lax.scan(body, (x0, st), jnp.arange(10))
        )(x, state)
        assert np.isfinite(np.asarray(xf)).all()


def test_add_noise_ddim_matches_closed_form():
    """add_noise must land exactly on x_t = sqrt(ac_t) x0 + sqrt(1-ac_t) n at
    the step's timestep (img2img entry)."""
    s = DDIMScheduler().set_timesteps(10)
    key = jax.random.PRNGKey(3)
    x0 = jax.random.normal(key, (1, 4, 4, 2))
    n = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    for i in (0, 4, 9):
        t = int(np.asarray(s.timesteps())[i])
        ac = s._alphas_cumprod[t]
        want = np.sqrt(ac) * np.asarray(x0) + np.sqrt(1 - ac) * np.asarray(n)
        np.testing.assert_allclose(np.asarray(s.add_noise(x0, n, i)), want,
                                   rtol=1e-6, atol=1e-6)


def test_add_noise_euler_sigma_space():
    s = EulerDiscreteScheduler().set_timesteps(10)
    key = jax.random.PRNGKey(4)
    x0 = jax.random.normal(key, (1, 4, 4, 2))
    n = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    for i in (0, 5):
        sigma = float(np.asarray(s._sigmas)[i])
        want = np.asarray(x0) + sigma * np.asarray(n)
        np.testing.assert_allclose(np.asarray(s.add_noise(x0, n, i)), want,
                                   rtol=1e-6, atol=1e-6)
    # at i=0 this is the init_noise_sigma-scaled entry up to the +x0 shift
    assert float(np.asarray(s._sigmas)[0]) == pytest.approx(
        (s.init_noise_sigma**2 - 1) ** 0.5, rel=1e-6)


def test_add_noise_then_oracle_denoise_recovers_x0():
    """End-to-end img2img sanity: noise a clean latent to the midpoint, then
    denoise the remaining steps with the true-noise oracle — DDIM must land
    back on x0 (the trajectory is exact for an oracle model)."""
    s = DDIMScheduler().set_timesteps(8)
    key = jax.random.PRNGKey(5)
    x0 = jax.random.normal(key, (1, 4, 4, 2))
    n = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    start = 4
    x = s.add_noise(x0, n, start)
    state = s.init_state(x0.shape)
    for i in range(start, 8):
        x, state = s.step(x, n, i, state)  # oracle: model predicts n exactly
    # set_alpha_to_one=False: the trajectory terminates at alpha = ac[0]
    # (x_final = sqrt(ac0) x0 + sqrt(1-ac0) n), not exactly x0
    a_last = float(np.asarray(s._alpha_prev)[-1])
    want = np.sqrt(a_last) * np.asarray(x0) + np.sqrt(1 - a_last) * np.asarray(n)
    np.testing.assert_allclose(np.asarray(x), want, rtol=1e-4, atol=1e-4)

"""Collective helpers under shard_map on the fake 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from distrifuser_tpu.utils.compat import shard_map

from distrifuser_tpu.parallel import collectives as col
from distrifuser_tpu.utils.config import SP_AXIS


def sp_mesh(devices, n):
    return Mesh(np.array(devices[:n]).reshape(n), axis_names=(SP_AXIS,))


def test_halo_exchange_matches_neighbors(devices8):
    n, b, h, w, c, halo = 4, 1, 6, 5, 3, 2
    mesh = sp_mesh(devices8, n)
    x = jnp.arange(b * n * h * w * c, dtype=jnp.float32).reshape(b, n * h, w, c)

    def f(xl):
        fp, fn = col.halo_exchange(xl, halo, n)
        return fp, fn

    fp, fn = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P(None, SP_AXIS), out_specs=P(None, SP_AXIS))
    )(x)
    fp = np.asarray(fp).reshape(n, b, halo, w, c)  # concat over sp gave n*halo rows
    fn = np.asarray(fn).reshape(n, b, halo, w, c)
    xg = np.asarray(x).reshape(b, n, h, w, c).transpose(1, 0, 2, 3, 4)
    for i in range(n):
        want_prev = xg[i - 1][:, -halo:] if i > 0 else np.zeros_like(fp[i])
        want_next = xg[i + 1][:, :halo] if i < n - 1 else np.zeros_like(fn[i])
        np.testing.assert_array_equal(fp[i], want_prev)
        np.testing.assert_array_equal(fn[i], want_next)


def test_gather_rows_roundtrip(devices8):
    n = 8
    mesh = sp_mesh(devices8, n)
    x = jnp.arange(2 * 16 * 3 * 2, dtype=jnp.float32).reshape(2, 16, 3, 2)

    out = jax.jit(
        shard_map(
            lambda xl: col.gather_rows(xl),
            mesh=mesh,
            in_specs=P(None, SP_AXIS),
            out_specs=P(None, None),  # replicated full tensor
            check_vma=False,
        )
    )(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_gather_cols_roundtrip(devices8):
    n = 4
    mesh = sp_mesh(devices8, n)
    x = jnp.arange(1 * 6 * 8 * 2, dtype=jnp.float32).reshape(1, 6, 8, 2)
    out = jax.jit(
        shard_map(
            lambda xl: col.gather_cols(xl),
            mesh=mesh,
            in_specs=P(None, None, SP_AXIS),
            out_specs=P(None, None, None),
            check_vma=False,
        )
    )(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_all_gather_seq(devices8):
    n = 4
    mesh = sp_mesh(devices8, n)
    x = jnp.arange(2 * 12 * 3, dtype=jnp.float32).reshape(2, 12, 3)
    out = jax.jit(
        shard_map(
            lambda xl: col.all_gather_seq(xl),
            mesh=mesh,
            in_specs=P(None, SP_AXIS, None),
            out_specs=P(None, None, None),
            check_vma=False,
        )
    )(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_psum_mean(devices8):
    n = 8
    mesh = sp_mesh(devices8, n)
    x = jnp.arange(n, dtype=jnp.float32).reshape(n, 1)
    out = jax.jit(
        shard_map(
            lambda xl: col.psum_mean(xl),
            mesh=mesh,
            in_specs=P(SP_AXIS, None),
            out_specs=P(SP_AXIS, None),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((n, 1), np.mean(range(n))))


def test_ring_perm_covers_every_peer_once():
    n = 4
    perm = col.ring_perm(n)
    assert perm == [(0, 1), (1, 2), (2, 3), (3, 0)]
    # n-1 hops deliver device r-h mod n to rank r, every peer exactly once
    for r in range(n):
        seen = set()
        src = r
        for _ in range(n - 1):
            src = (src - 1) % n
            seen.add(src)
        assert seen == set(range(n)) - {r}


def test_ring_shift_rotates_one_hop(devices8):
    n = 4
    mesh = sp_mesh(devices8, n)
    x = jnp.arange(n, dtype=jnp.float32).reshape(n, 1)
    out = jax.jit(
        shard_map(
            lambda xl: col.ring_shift(xl, n),
            mesh=mesh,
            in_specs=P(SP_AXIS, None),
            out_specs=P(SP_AXIS, None),
        )
    )(x)
    # rank r receives rank r-1's value (wrap at 0)
    np.testing.assert_array_equal(
        np.asarray(out).ravel(), np.array([3.0, 0.0, 1.0, 2.0])
    )


def test_pipelined_ring_pass_permute_is_deferred(devices8):
    """FastUSP-style overlap, checked structurally: the software-pipelined
    ring_pass issues hop i+1's ppermute before merging hop i's arrival, so
    in the compiled while body the collective-permute's value reaches ONLY
    the loop carry — utils/overlap.py classifies it deferred
    (overlappable), where the serial ring's permute (consumed by the same
    iteration's score matmuls) classified inline."""
    from distrifuser_tpu.ops.ring_attention import ring_pass
    from distrifuser_tpu.utils.overlap import analyze_loop_collectives

    n, b, L, c, heads = 4, 1, 256, 64, 4
    mesh = sp_mesh(devices8, n)
    q = jnp.zeros((b, L, c))
    kv = jnp.zeros((b, L, 2 * c))
    sm = shard_map(
        lambda ql, kvl: ring_pass(ql, kvl, kvl, n, SP_AXIS, heads=heads),
        mesh=mesh,
        in_specs=(P(None, SP_AXIS), P(None, SP_AXIS)),
        out_specs=P(None, None, SP_AXIS),
    )
    hlo = jax.jit(sm).lower(q, kv).compile().as_text()
    reports = analyze_loop_collectives(hlo)
    assert reports, "ring fori_loop produced no while-body collectives"
    ring = max(reports, key=lambda r: r.n_deferred)
    assert "collective-permute" in ring.deferred.values(), (
        f"pipelined ring hop not carry-only: {ring.inline}"
    )
    assert ring.n_inline == 0, (
        f"ring while body serializes a collective against compute: "
        f"{ring.inline}"
    )

"""Resilience-layer tests (serve/errors.py, serve/faults.py,
serve/resilience.py + their server integration): typed errors, backoff
schedule math (injected clock/seed — no sleeps), circuit transitions,
watchdog, deterministic fault injection, batch-split bit-identity, and
degradation-ladder ordering.  Weightless fakes only — no devices, no
compiles; the real-pipeline adapter path is covered by
test_serve_pipeline.py."""

import threading
import time

import numpy as np
import pytest

from distrifuser_tpu.serve import (
    BackoffPolicy,
    BuildFailedError,
    CircuitBreaker,
    CircuitOpenError,
    DeadlineExceededError,
    DegradationLadder,
    ExecKey,
    ExecuteFailedError,
    ExecutorCache,
    FatalError,
    FaultPlan,
    FaultRule,
    InferenceServer,
    NoBucketError,
    QueueFullError,
    ResilienceConfig,
    ResourceExhaustedError,
    RetryBudget,
    RetryableError,
    ServeConfig,
    ServeError,
    ServerClosedError,
    Watchdog,
    WatchdogTimeoutError,
)
from distrifuser_tpu.serve.faults import (
    InjectedCompileError,
    InjectedExecuteError,
    InjectedFault,
    InjectedResourceExhausted,
)
from distrifuser_tpu.serve.resilience import (
    RUNG_BUCKET,
    RUNG_SPLIT,
    RUNG_STEP_CACHE_OFF,
    RUNG_STEPWISE,
    KeyResilience,
    failure_kind,
)
from distrifuser_tpu.serve.testing import FakeExecutor, FakeExecutorFactory, fake_image
from distrifuser_tpu.utils.metrics import RingLog


def key_for(h=512, w=512, steps=4, **kw):
    kw.setdefault("model_id", "m")
    kw.setdefault("scheduler", "ddim")
    kw.setdefault("cfg", True)
    kw.setdefault("mesh_plan", "dp1.cfg1.sp1")
    return ExecKey(height=h, width=w, steps=steps, **kw)


def serve_config(**kw):
    kw.setdefault("max_queue_depth", 16)
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("batch_window_s", 0.2)
    kw.setdefault("buckets", ((512, 512), (1024, 1024)))
    kw.setdefault("default_steps", 4)
    kw.setdefault("resilience", fast_resilience())
    return ServeConfig(**kw)


def fast_resilience(**kw):
    kw.setdefault("max_retries", 2)
    kw.setdefault("backoff_base_s", 0.001)
    kw.setdefault("backoff_max_s", 0.002)
    kw.setdefault("backoff_jitter", 0.0)
    kw.setdefault("breaker_failure_threshold", 3)
    kw.setdefault("breaker_cooldown_s", 0.2)
    kw.setdefault("watchdog_timeout_s", 5.0)
    return ResilienceConfig(**kw)


# --------------------------------------------------------------------------
# typed error hierarchy
# --------------------------------------------------------------------------


def test_error_hierarchy_retryable_vs_fatal():
    for cls in (QueueFullError, CircuitOpenError, WatchdogTimeoutError,
                BuildFailedError, ExecuteFailedError, ResourceExhaustedError):
        assert issubclass(cls, RetryableError), cls
        assert not issubclass(cls, FatalError), cls
    for cls in (DeadlineExceededError, ServerClosedError, NoBucketError):
        assert issubclass(cls, FatalError), cls
        assert not issubclass(cls, RetryableError), cls
    assert issubclass(ResourceExhaustedError, ExecuteFailedError)
    for cls in (RetryableError, FatalError):
        assert issubclass(cls, ServeError)


def test_failure_kind_classification():
    assert failure_kind(ResourceExhaustedError("RESOURCE_EXHAUSTED")) == "oom"
    assert failure_kind(
        ExecuteFailedError("RESOURCE_EXHAUSTED: oom-shaped message")) == "oom"
    # build failures are "compile" even when memory-shaped: the remedy is
    # a cheaper program, not a narrower batch
    assert failure_kind(
        BuildFailedError("RESOURCE_EXHAUSTED during compile")) == "compile"
    assert failure_kind(ExecuteFailedError("boom")) == "transient"
    assert failure_kind(WatchdogTimeoutError("hung")) == "transient"
    assert failure_kind(DeadlineExceededError("late")) == "fatal"


# --------------------------------------------------------------------------
# backoff schedule math (no sleeps)
# --------------------------------------------------------------------------


def test_backoff_schedule_exponential_and_capped():
    p = BackoffPolicy(base_s=0.1, multiplier=2.0, max_s=0.5, jitter=0.0)
    assert p.schedule(5) == [0.1, 0.2, 0.4, 0.5, 0.5]


def test_backoff_jitter_bounded_and_deterministic():
    a = BackoffPolicy(0.1, 2.0, 10.0, jitter=0.25, seed=7)
    b = BackoffPolicy(0.1, 2.0, 10.0, jitter=0.25, seed=7)
    sa, sb = a.schedule(50), b.schedule(50)
    assert sa == sb  # seeded: identical streams
    for i, d in enumerate(sa):
        nominal = min(0.1 * 2.0 ** i, 10.0)
        assert nominal * 0.75 <= d <= nominal * 1.25
    c = BackoffPolicy(0.1, 2.0, 10.0, jitter=0.25, seed=8)
    assert c.schedule(50) != sa  # different seed, different jitter


def test_retry_budget_exhausts():
    b = RetryBudget(2)  # refill_per_s=0: strict lifetime cap
    assert b.acquire() and b.acquire()
    assert not b.acquire()
    assert b.remaining == 0


def test_retry_budget_refills_on_injected_clock():
    t = [0.0]
    b = RetryBudget(2, refill_per_s=0.5, clock=lambda: t[0])
    assert b.acquire() and b.acquire() and not b.acquire()
    t[0] = 1.0  # 0.5 tokens accrued: still under one whole token
    assert not b.acquire()
    t[0] = 2.0  # 1.0 token
    assert b.acquire() and not b.acquire()
    t[0] = 100.0  # refill clamps at the bucket size
    assert b.remaining == 2
    assert b.acquire() and b.acquire() and not b.acquire()


# --------------------------------------------------------------------------
# circuit breaker (injected clock — no sleeps)
# --------------------------------------------------------------------------


def test_circuit_closed_open_half_open_close():
    t = [0.0]
    br = CircuitBreaker(failure_threshold=2, cooldown_s=10.0,
                        clock=lambda: t[0])
    assert br.state() == "closed" and br.allow()
    br.record_failure()
    assert br.state() == "closed" and br.allow()  # below threshold
    br.record_failure()
    assert br.state() == "open" and not br.allow()
    t[0] = 9.9
    assert not br.allow()  # cooldown not elapsed
    t[0] = 10.0
    assert br.state() == "half_open"
    assert br.allow()  # the single probe
    assert not br.allow()  # second caller sheds while probe in flight
    br.record_success()
    assert br.state() == "closed" and br.allow()
    assert br.times_opened == 1


def test_circuit_failed_probe_reopens():
    t = [0.0]
    br = CircuitBreaker(failure_threshold=1, cooldown_s=5.0,
                        clock=lambda: t[0])
    br.record_failure()
    assert br.state() == "open"
    t[0] = 5.0
    assert br.allow()  # probe
    br.record_failure()  # probe failed
    assert br.state() == "open" and not br.allow()
    t[0] = 9.9  # cooldown re-armed at t=5
    assert not br.allow()
    t[0] = 10.0
    assert br.allow()
    br.record_success()
    assert br.state() == "closed"
    assert br.snapshot()["times_opened"] == 2


def test_success_resets_consecutive_failures():
    br = CircuitBreaker(failure_threshold=3, cooldown_s=1.0)
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state() == "closed"  # never 3 consecutive


# --------------------------------------------------------------------------
# watchdog
# --------------------------------------------------------------------------


def test_watchdog_passes_result_and_exceptions_through():
    wd = Watchdog(timeout_s=5.0)
    assert wd.run(lambda: 42) == 42
    with pytest.raises(ValueError, match="inner"):
        wd.run(lambda: (_ for _ in ()).throw(ValueError("inner")))
    assert wd.timeouts == 0


def test_watchdog_fires_on_hang_without_blocking():
    wd = Watchdog(timeout_s=0.05)
    t0 = time.monotonic()
    with pytest.raises(WatchdogTimeoutError):
        wd.run(lambda: time.sleep(0.5))
    assert time.monotonic() - t0 < 0.4  # did NOT wait out the hang
    assert wd.timeouts == 1


def test_watchdog_disabled_runs_inline():
    wd = Watchdog(timeout_s=0.0)
    tid = wd.run(lambda: threading.get_ident())
    assert tid == threading.get_ident()


def test_watchdog_serializes_behind_abandoned_worker():
    """A retry after an abandonment must never overlap the stuck call's
    work: the next run() waits for the abandoned worker to drain (and
    sheds if it doesn't), so the mesh sees one dispatch at a time."""
    wd = Watchdog(timeout_s=0.15)
    active = [0]
    peak = [0]
    lock = threading.Lock()

    def tracked(extra_s):
        def fn():
            with lock:
                active[0] += 1
                peak[0] = max(peak[0], active[0])
            time.sleep(extra_s)
            with lock:
                active[0] -= 1
            return "done"
        return fn

    with pytest.raises(WatchdogTimeoutError):
        wd.run(tracked(0.25))  # abandoned at 0.15, drains at 0.25 — well
        # inside the retry's 0.15s grace window (not at its boundary)
    # retry while the abandoned worker still runs: waits for it, then
    # executes — never concurrently (peak stays 1)
    assert wd.run(tracked(0.0)) == "done"
    assert peak[0] == 1
    # a still-stuck abandoned worker sheds the next dispatch instead
    wd2 = Watchdog(timeout_s=0.05)
    with pytest.raises(WatchdogTimeoutError):
        wd2.run(tracked(10.0))
    with pytest.raises(WatchdogTimeoutError, match="abandoned"):
        wd2.run(tracked(0.0))
    assert wd2.timeouts == 2


# --------------------------------------------------------------------------
# fault plan: determinism and filters
# --------------------------------------------------------------------------


def test_fault_plan_at_calls_exact():
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                at_calls=(1, 3))])
    fired = []
    for i in range(5):
        try:
            plan.check("execute")
            fired.append(False)
        except InjectedExecuteError:
            fired.append(True)
    assert fired == [False, True, False, True, False]
    assert plan.fired() == {"execute/execute_error": 2}


def test_fault_plan_seeded_probability_is_deterministic():
    def pattern(seed):
        plan = FaultPlan([FaultRule(site="execute", kind="oom", p=0.3)],
                         seed=seed)
        out = []
        for _ in range(100):
            try:
                plan.check("execute")
                out.append(0)
            except InjectedResourceExhausted:
                out.append(1)
        return out

    a, b, c = pattern(0), pattern(0), pattern(1)
    assert a == b
    assert a != c
    assert 10 < sum(a) < 60  # p=0.3 over 100 calls, loose bounds


def test_fault_plan_min_batch_and_max_fires():
    plan = FaultPlan([FaultRule(site="execute", kind="oom", p=1.0,
                                min_batch=3, max_fires=2)])
    plan.check("execute", batch_size=2)  # below min_batch: no fire
    for _ in range(2):
        with pytest.raises(InjectedResourceExhausted):
            plan.check("execute", batch_size=4)
    plan.check("execute", batch_size=4)  # max_fires exhausted
    assert plan.fired() == {"execute/oom": 2}


def test_fault_plan_key_substr_filter():
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error", p=1.0,
                                key_substr="1024x1024")])
    plan.check("execute", key=key_for(512, 512))  # no match, no fire
    with pytest.raises(InjectedExecuteError):
        plan.check("execute", key=key_for(1024, 1024))


def test_injected_oom_is_oom_shaped():
    from distrifuser_tpu.serve.errors import is_oom

    exc = pytest.raises(InjectedResourceExhausted, FaultPlan(
        [FaultRule(site="s", kind="oom", p=1.0)]).check, "s").value
    assert is_oom(exc)
    assert isinstance(exc, InjectedFault)


def test_fault_rule_validation():
    with pytest.raises(ValueError, match="fault kind"):
        FaultRule(site="s", kind="nope", p=0.5)
    with pytest.raises(ValueError, match="probability"):
        FaultRule(site="s", kind="oom", p=1.5)
    with pytest.raises(ValueError, match="never fire"):
        FaultRule(site="s", kind="oom")


# --------------------------------------------------------------------------
# degradation ladder: ordering (pure math)
# --------------------------------------------------------------------------


def ladder(**kw):
    buckets = kw.pop("buckets", ((512, 512), (1024, 1024)))
    return DegradationLadder(fast_resilience(**kw), buckets)


def test_ladder_oom_splits_first():
    st = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    lad = ladder()
    assert lad.next_rung(st, "oom", key_for(), batch_size=4) == RUNG_SPLIT
    # singletons cannot split: first key rung instead
    k = key_for(step_cache_interval=2, step_cache_depth=1)
    assert lad.next_rung(st, "oom", k, batch_size=1) == RUNG_STEP_CACHE_OFF


def test_ladder_compile_never_splits():
    st = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    k = key_for(step_cache_interval=2, step_cache_depth=1)
    assert ladder().next_rung(st, "compile", k,
                              batch_size=4) == RUNG_STEP_CACHE_OFF


def test_ladder_ordering_cache_off_then_stepwise_then_bucket():
    st = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    lad = ladder(allow_bucket_fallback=True)
    k = key_for(1024, 1024, step_cache_interval=2, step_cache_depth=1)
    order = []
    for _ in range(5):
        rung = lad.next_rung(st, "compile", k, batch_size=1)
        if rung is None:
            break
        st.rungs.append(rung)
        order.append(rung)
    assert order == [RUNG_STEP_CACHE_OFF, RUNG_STEPWISE, RUNG_BUCKET]
    dk = lad.apply(k, st.rungs)
    assert (dk.step_cache_interval, dk.step_cache_depth) == (1, 0)
    assert dk.exec_mode == "stepwise"
    assert (dk.height, dk.width) == (512, 512)  # next smaller bucket


def test_ladder_respects_config_gates_and_cap():
    st = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    k = key_for(1024, 1024, step_cache_interval=2, step_cache_depth=1)
    # everything gated off: ladder exhausted immediately
    lad = ladder(allow_batch_split=False, allow_step_cache_off=False,
                 allow_stepwise_fallback=False)
    assert lad.next_rung(st, "oom", k, batch_size=4) is None
    # max_degradations caps the rung count
    lad2 = ladder(max_degradations=1)
    st2 = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    st2.rungs.append(RUNG_STEP_CACHE_OFF)
    assert lad2.next_rung(st2, "compile", k, batch_size=1) is None
    # no smaller bucket for the smallest key
    lad3 = ladder(allow_bucket_fallback=True, allow_step_cache_off=False,
                  allow_stepwise_fallback=False)
    assert lad3.next_rung(
        KeyResilience(breaker=CircuitBreaker(3, 1.0)), "compile",
        key_for(512, 512), batch_size=1) is None


def test_exec_key_stepwise_mode_and_short():
    k = key_for(exec_mode="stepwise")
    assert "stepwise" in k.short()
    assert "stepwise" not in key_for().short()
    with pytest.raises(ValueError, match="exec_mode"):
        key_for(exec_mode="warp")


def test_exec_key_pipefusion_fields_and_short():
    """parallelism/pipe_patches are compile-identity fields: distinct
    short() tags (the per-executor ledgers key on them) and the invalid
    combinations reject at construction."""
    k = key_for(parallelism="pipefusion", pipe_patches=8)
    assert k.short().endswith(":pf8")
    assert ":pf" not in key_for().short()
    assert key_for(parallelism="pipefusion").short().endswith(":pf")
    with pytest.raises(ValueError, match="pipe_patches"):
        key_for(pipe_patches=4)  # pipefusion-only field on a patch key
    with pytest.raises(ValueError, match="pipeline_off"):
        key_for(parallelism="pipefusion", exec_mode="stepwise")
    with pytest.raises(ValueError, match="parallelism"):
        key_for(parallelism="tensor")


def test_ladder_pipefusion_routes_to_pipeline_off_not_stepwise():
    """A failing pipefusion key degrades via pipeline_off — rebuilding as
    EXACTLY the patch bucket's key — never via stepwise (no host-driven
    loop exists there); once on patch, the normal program rungs resume."""
    from distrifuser_tpu.serve.resilience import RUNG_PIPELINE_OFF

    st = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    lad = ladder()
    k = key_for(parallelism="pipefusion", pipe_patches=8)
    rung = lad.next_rung(st, "oom", k, batch_size=1)
    assert rung == RUNG_PIPELINE_OFF
    st.rungs.append(rung)
    assert lad.apply(k, st.rungs) == key_for()  # the fresh patch key
    # the degraded key is patch now: stepwise becomes applicable
    assert lad.next_rung(st, "compile", k, batch_size=1) == RUNG_STEPWISE
    # rung gated off -> the ladder must NOT detour to stepwise for a
    # still-pipefusion key; with everything else at defaults it exhausts
    st2 = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    assert ladder(allow_pipeline_off=False).next_rung(
        st2, "oom", k, batch_size=1) is None


def test_pipeline_off_ladder_under_oom_isolated_to_its_key():
    """ISSUE-7 acceptance: a pipefusion bucket that OOMs falls to the
    patch key via the pipeline_off rung and completes, while an unrelated
    pipefusion bucket keeps serving pipeline-parallel, untripped."""
    import dataclasses

    built = []

    class PipeOOMFake(FakeExecutor):
        def __call__(self, prompts, negatives, gs, seeds):
            if (self.key.parallelism == "pipefusion"
                    and self.key.height == 512):
                raise InjectedResourceExhausted(
                    "RESOURCE_EXHAUSTED: pipeline stage HBM")
            return super().__call__(prompts, negatives, gs, seeds)

    def factory(key):
        built.append(key)
        return PipeOOMFake(key, batch_size=4)

    cfg = serve_config(parallelism="pipefusion", pipe_patches=4)
    with InferenceServer(factory, cfg) as server:
        r1 = server.submit("a", height=512, width=512).result(timeout=30)
        r2 = server.submit("b", height=1024, width=1024).result(timeout=30)
        snap = server.metrics_snapshot()
        health = server.health()
    assert r1.degradations == ("pipeline_off",)
    assert r2.degradations == ()
    keys_512 = [k for k in built if k.height == 512]
    assert [k.parallelism for k in keys_512] == ["pipefusion", "patch"]
    # the rebuilt key IS the fresh patch key for the bucket
    assert keys_512[1] == dataclasses.replace(
        keys_512[0], parallelism="patch", pipe_patches=0)
    keys_1024 = [k for k in built if k.height == 1024]
    assert [k.parallelism for k in keys_1024] == ["pipefusion"]
    assert snap["requests"]["degraded_pipeline_off"] == 1
    (tag,) = snap["resilience"]["degradations"].keys()
    assert tag.endswith(":pf4") and "512" in tag


# --------------------------------------------------------------------------
# cache invalidation + ring log
# --------------------------------------------------------------------------


def test_cache_invalidate_drops_and_rebuilds():
    evicted = []
    cache = ExecutorCache(lambda k: object(), capacity=4,
                          on_evict=lambda k, e: evicted.append(k))
    k = key_for()
    ex1, hit = cache.get(k)
    assert not hit
    assert cache.invalidate(k)
    assert evicted == [k]
    assert not cache.invalidate(k)  # already gone
    ex2, hit = cache.get(k)
    assert not hit and ex2 is not ex1  # rebuilt, not resurrected


def test_ring_log_bounded():
    log = RingLog(capacity=3)
    for i in range(7):
        log.add(f"e{i}")
    snap = log.snapshot()
    assert [e["message"] for e in snap] == ["e4", "e5", "e6"]
    assert [e["seq"] for e in snap] == [5, 6, 7]
    assert len(log) == 3 and log.total == 7


# --------------------------------------------------------------------------
# server integration: retry, watchdog, breaker, split, ladder, health
# --------------------------------------------------------------------------


def test_server_retries_transient_execute_error():
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                at_calls=(0,))])
    factory = FakeExecutorFactory(batch_size=4)
    with InferenceServer(factory, serve_config(), fault_plan=plan) as server:
        r = server.submit("p", height=512, width=512, seed=3).result(timeout=30)
    assert (r.output == fake_image("p", 3, factory.built[0])).all()
    assert r.retries == 1 and r.degradations == ()
    snap = server.metrics_snapshot()
    assert snap["requests"]["retries"] == 1
    assert snap["requests"]["completed"] == 1
    assert snap["requests"].get("scheduler_errors", 0) == 0
    # a retried-then-successful dispatch is NOT a breaker failure: the
    # breaker counts terminal outcomes, and this batch's outcome was good
    (circuit,) = snap["resilience"]["circuits"].values()
    assert circuit["consecutive_failures"] == 0
    assert circuit["state"] == "closed"


def test_server_watchdog_bounds_injected_hang():
    # hang 0.35s vs 0.2s watchdog: the first dispatch is abandoned at
    # 0.2s; the retry serializes behind the abandoned worker (drains at
    # 0.35s, inside its 0.2s grace) and then succeeds
    plan = FaultPlan([FaultRule(site="execute", kind="hang", at_calls=(0,),
                                hang_s=0.35)])
    cfg = serve_config(resilience=fast_resilience(watchdog_timeout_s=0.2))
    factory = FakeExecutorFactory(batch_size=4)
    t0 = time.monotonic()
    with InferenceServer(factory, cfg, fault_plan=plan) as server:
        r = server.submit("p", height=512, width=512).result(timeout=30)
    assert time.monotonic() - t0 < 3.0  # nowhere near the 5s hang
    assert r.retries == 1
    snap = server.metrics_snapshot()
    assert snap["requests"]["watchdog_timeouts"] == 1
    assert snap["resilience"]["watchdog_timeouts"] == 1
    health = server.health()
    # the scheduler survived the hang (it is stopped now, but it was
    # never killed: the stop() join succeeded and all work completed)
    assert snap["requests"].get("scheduler_errors", 0) == 0


def test_server_circuit_opens_sheds_fast_then_recovers():
    # the breaker counts TERMINAL dispatch failures: request 1 exhausts
    # its retries (2 attempts, rule max_fires=2) = one terminal failure =
    # threshold, tripping the breaker; the key is healthy afterwards, so
    # the half-open probe after the cooldown heals it.
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error", p=1.0,
                                max_fires=2)])
    # batch_window_s=0: the breaker is consulted at DISPATCH time, so a
    # linger window longer than the cooldown would let the breaker go
    # half-open before the shed check ever runs
    cfg = serve_config(batch_window_s=0.0, resilience=fast_resilience(
        max_retries=1, breaker_failure_threshold=1, breaker_cooldown_s=0.2))
    factory = FakeExecutorFactory(batch_size=4)
    with InferenceServer(factory, cfg, fault_plan=plan) as server:
        with pytest.raises(ExecuteFailedError):
            server.submit("poisoned", height=512, width=512).result(timeout=30)
        t0 = time.monotonic()
        with pytest.raises(CircuitOpenError):
            server.submit("shed-me", height=512, width=512).result(timeout=30)
        shed_elapsed = time.monotonic() - t0
        assert shed_elapsed < 1.0  # the acceptance bound: no queue burn
        assert server.health()["status"] == "degraded"
        assert server.health()["open_circuits"]
        time.sleep(0.3)  # past the cooldown: half-open
        r = server.submit("probe", height=512, width=512).result(timeout=30)
        assert r.output is not None
        health = server.health()
    assert health["status"] == "ok"  # breaker closed by the probe
    snap = server.metrics_snapshot()
    assert snap["requests"]["shed_circuit_open"] == 1
    assert snap["requests"]["failed_execute"] == 1


def test_server_batch_split_retry_bit_identical():
    # OOM whenever the coalesced batch reaches 3+: the 4-wide batch must
    # split into halves and every request's image must equal the
    # weightless fake's pure function of (prompt, seed, key) — i.e. be
    # bit-identical to what the unsplit batch would have produced.
    plan = FaultPlan([FaultRule(site="execute", kind="oom", p=1.0,
                                min_batch=3)])
    factory = FakeExecutorFactory(batch_size=4)
    cfg = serve_config(batch_window_s=0.3)
    with InferenceServer(factory, cfg, fault_plan=plan) as server:
        futs = []
        lock = threading.Lock()

        def client(i):
            f = server.submit(f"p{i}", height=512, width=512, seed=i)
            with lock:
                futs.append((i, f))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        results = {i: f.result(timeout=30) for i, f in futs}
        # sticky cap: the next wave must coalesce to at most 2 directly
        wave2 = [server.submit(f"w{i}", height=512, width=512, seed=10 + i)
                 for i in range(4)]
        for f in wave2:
            f.result(timeout=30)
    key = factory.built[0]
    for i, r in results.items():
        np.testing.assert_array_equal(r.output, fake_image(f"p{i}", i, key))
        assert r.batch_size <= 2  # executed in a split half
    assert max(factory.batch_sizes()) <= 2  # OOM width never executed
    snap = server.metrics_snapshot()
    assert snap["requests"]["degraded_split_batch"] >= 1
    caps = snap["resilience"]["degradations"]
    assert [d["batch_cap"] for d in caps.values()] == [2]


def test_server_degradation_ladder_walk_on_build_failures():
    # the cadence program OOMs at build, the fused cache-off program
    # fails to compile, the stepwise program builds: the ladder must walk
    # step_cache_off -> stepwise_fallback IN ORDER within one request.
    built = []

    def factory(key):
        built.append(key)
        if key.step_cache_interval > 1:
            raise InjectedResourceExhausted(
                "RESOURCE_EXHAUSTED: no HBM for the cadence program")
        if key.exec_mode == "fused":
            raise InjectedCompileError("fused compile failed")
        return FakeExecutor(key, batch_size=4)

    cfg = serve_config(
        step_cache_interval=2, step_cache_depth=1,
        resilience=fast_resilience(max_retries=3),
    )
    with InferenceServer(factory, cfg) as server:
        r = server.submit("p", height=512, width=512).result(timeout=30)
        # second request goes straight to the degraded key: no retries
        r2 = server.submit("q", height=512, width=512).result(timeout=30)
        health = server.health()
    assert r.degradations == (RUNG_STEP_CACHE_OFF, RUNG_STEPWISE)
    assert r.retries == 2 and r2.retries == 0
    assert [k.exec_mode for k in built] == ["fused", "fused", "stepwise"]
    assert built[1].step_cache_interval == 1  # cache off before stepwise
    assert built[2].step_cache_interval == 1
    (entry,) = health["degradations"].values()
    assert entry["rungs"] == [RUNG_STEP_CACHE_OFF, RUNG_STEPWISE]
    assert health["status"] == "degraded"
    snap = server.metrics_snapshot()
    assert snap["requests"]["degraded_step_cache_off"] == 1
    assert snap["requests"]["degraded_stepwise_fallback"] == 1


def test_warmup_build_failure_does_not_abort_startup():
    """A failed warmup compile is recorded, not fatal: the server comes
    up, and the first request rebuilds the bucket through the retry
    machinery."""
    plan = FaultPlan([FaultRule(site="build", kind="compile_error",
                                at_calls=(0,))])
    factory = FakeExecutorFactory(batch_size=4)
    cfg = serve_config(warmup_buckets=((512, 512, 4),))
    with InferenceServer(factory, cfg, fault_plan=plan) as server:
        health = server.health()
        assert health["scheduler_alive"]
        r = server.submit("p", height=512, width=512).result(timeout=30)
    assert r.output is not None and not r.compile_hit
    snap = server.metrics_snapshot()
    assert snap["requests"]["warmup_build_failures"] == 1
    assert snap["requests"]["completed"] == 1
    assert len(snap["resilience"]["last_errors"]) == 1  # the warmup failure


def test_server_build_failure_exhausts_retries_with_typed_error():
    def factory(key):
        raise RuntimeError("flaky compile service")

    cfg = serve_config(resilience=fast_resilience(max_retries=1))
    with InferenceServer(factory, cfg) as server:
        fut = server.submit("p", height=512, width=512)
        with pytest.raises(BuildFailedError, match="flaky compile service"):
            fut.result(timeout=30)
    snap = server.metrics_snapshot()
    assert snap["requests"]["failed_build"] == 1
    assert snap["requests"]["retries"] == 1  # one retry, then typed failure


def test_server_retry_budget_bounds_total_retries():
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error", p=1.0)])
    cfg = serve_config(resilience=fast_resilience(
        max_retries=5, retry_budget=2, breaker_failure_threshold=100))
    factory = FakeExecutorFactory(batch_size=4)
    with InferenceServer(factory, cfg, fault_plan=plan) as server:
        with pytest.raises(ExecuteFailedError):
            server.submit("p", height=512, width=512).result(timeout=30)
    snap = server.metrics_snapshot()
    assert snap["requests"]["retries"] == 2  # budget, not max_retries, bound
    assert snap["requests"]["retry_budget_exhausted"] == 1
    assert snap["resilience"]["retry_budget_remaining"] == 0


def test_server_stop_interrupts_backoff_sleep():
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error", p=1.0)])
    cfg = serve_config(resilience=fast_resilience(
        max_retries=5, backoff_base_s=30.0, backoff_max_s=30.0))
    factory = FakeExecutorFactory(batch_size=4)
    server = InferenceServer(factory, cfg, fault_plan=plan).start(warmup=False)
    fut = server.submit("p", height=512, width=512)
    time.sleep(0.3)  # scheduler is now asleep in a 30s backoff
    t0 = time.monotonic()
    server.stop(timeout=10.0)
    assert time.monotonic() - t0 < 5.0  # did NOT wait out the backoff
    with pytest.raises(ServerClosedError):
        fut.result(timeout=5)


def test_engine_key_state_is_lru_bounded_and_keeps_interesting_keys():
    from distrifuser_tpu.serve.resilience import ResilienceEngine

    engine = ResilienceEngine(fast_resilience(max_tracked_keys=2))
    k1, k2, k3 = key_for(steps=1), key_for(steps=2), key_for(steps=3)
    engine.key_state(k1).rungs.append(RUNG_STEPWISE)  # interesting
    engine.key_state(k2)  # boring
    engine.key_state(k3)  # exceeds the cap: the boring k2 is evicted
    snap = engine.snapshot()
    assert len(snap["circuits"]) == 2
    assert k1.short() in snap["circuits"] and k3.short() in snap["circuits"]
    assert engine.key_state(k1).rungs == [RUNG_STEPWISE]  # state survived


def test_engine_eviction_never_victimizes_the_new_key():
    """When every OLDER tracked key is interesting, the oldest other key
    is evicted — never the just-inserted one, whose state must survive
    within (and across) its own dispatch so its circuit can still trip."""
    from distrifuser_tpu.serve.resilience import ResilienceEngine

    engine = ResilienceEngine(fast_resilience(max_tracked_keys=2))
    k1, k2, k3 = key_for(steps=1), key_for(steps=2), key_for(steps=3)
    engine.key_state(k1).rungs.append(RUNG_STEPWISE)
    engine.key_state(k2).rungs.append(RUNG_STEP_CACHE_OFF)
    st3 = engine.key_state(k3)  # all older keys interesting: k1 (oldest
    st3.breaker.record_failure()  # other) goes, NOT the fresh k3
    assert engine.key_state(k3) is st3
    assert engine.key_state(k3).breaker.snapshot()["consecutive_failures"] == 1
    snap = engine.snapshot()
    assert k3.short() in snap["circuits"] and k2.short() in snap["circuits"]
    assert k1.short() not in snap["circuits"]


def test_stop_join_timeout_refuses_second_scheduler():
    """When stop()'s join times out (scheduler still draining a long
    dispatch), the thread handle must be kept: health() stays truthful
    and start() refuses to spawn a second scheduler over the mesh."""
    factory = FakeExecutorFactory(batch_size=4, step_time_s=0.2)  # 0.8s run
    server = InferenceServer(factory, serve_config(batch_window_s=0.0)).start()
    fut = server.submit("long", height=512, width=512)
    time.sleep(0.2)  # scheduler is now mid-dispatch
    server.stop(timeout=0.05)  # far shorter than the dispatch
    assert server.metrics_snapshot()["requests"]["stop_join_timeouts"] == 1
    assert server.health()["scheduler_alive"]  # truthfully still draining
    with pytest.raises(AssertionError, match="already started"):
        server.start()
    fut.result(timeout=10)  # the in-flight batch still completes
    server.stop(timeout=10.0)  # drained now: joins cleanly
    assert not server.health()["scheduler_alive"]
    # restart-after-stop is refused loudly: the queue is closed for good,
    # so a "restarted" server would reject 100% of traffic while
    # reporting a live scheduler
    with pytest.raises(ServerClosedError, match="build a new"):
        server.start()


def test_contract_violation_counts_as_breaker_failure():
    """A non-ServeError escape (executor contract violation) must still
    reach the breaker: a HALF_OPEN probe dying this way would otherwise
    leave the probe latch set forever, permanently shedding the key."""
    class Broken:
        batch_size = 4

        def __call__(self, prompts, negs, gs, seeds):
            return []  # violates the length contract

    with InferenceServer(lambda key: Broken(), serve_config()) as server:
        with pytest.raises(RuntimeError, match="outputs for a batch"):
            server.submit("p", height=512, width=512).result(timeout=30)
        health = server.health()
    (circuit,) = health["circuits"].values()
    assert circuit["consecutive_failures"] == 1
    assert server.counters.get("scheduler_errors") == 1


def test_set_stepwise_rejects_pipefusion():
    """The stepwise rung must fail LOUDLY for PipeFusion pipelines (no
    host-driven stepwise loop exists) instead of silently burning a
    degradation rung that changes nothing."""
    import types

    from distrifuser_tpu.pipelines import DistriPixArtPipeline

    class Shell(DistriPixArtPipeline):
        def __init__(self):  # the guard only reads distri_config
            self.distri_config = types.SimpleNamespace(
                parallelism="pipefusion", use_cuda_graph=True)

    with pytest.raises(ValueError, match="PipeFusion"):
        Shell().set_stepwise(True)
    patch = Shell()
    patch.distri_config.parallelism = "patch"
    patch.set_stepwise(True)
    assert patch.distri_config.use_cuda_graph is False


def test_health_snapshot_schema_and_json():
    import json

    factory = FakeExecutorFactory(batch_size=4)
    with InferenceServer(factory, serve_config()) as server:
        server.submit("p", height=512, width=512).result(timeout=30)
        health = server.health()
        assert health["scheduler_alive"]
    for section in ("status", "queue_depth", "scheduler_alive", "requests",
                    "circuits", "open_circuits", "degradations",
                    "retry_budget_remaining", "watchdog_timeouts",
                    "last_errors"):
        assert section in health, section
    assert health["status"] == "ok"
    json.dumps(health)  # JSON-serializable end to end
    snap = server.metrics_snapshot()
    assert "resilience" in snap
    json.dumps(snap)


def test_last_errors_recorded_in_health():
    plan = FaultPlan([FaultRule(site="execute", kind="execute_error",
                                at_calls=(0,))])
    factory = FakeExecutorFactory(batch_size=4)
    with InferenceServer(factory, serve_config(), fault_plan=plan) as server:
        server.submit("p", height=512, width=512).result(timeout=30)
        health = server.health()
    assert len(health["last_errors"]) == 1
    assert "ExecuteFailedError" in health["last_errors"][0]["message"]


def test_resilience_config_validation():
    with pytest.raises(ValueError, match="max_retries"):
        ResilienceConfig(max_retries=-1)
    with pytest.raises(ValueError, match="backoff_multiplier"):
        ResilienceConfig(backoff_multiplier=0.5)
    with pytest.raises(ValueError, match="backoff_jitter"):
        ResilienceConfig(backoff_jitter=1.0)
    with pytest.raises(ValueError, match="breaker_failure_threshold"):
        ResilienceConfig(breaker_failure_threshold=0)
    with pytest.raises(ValueError, match="resilience"):
        ServeConfig(resilience={"max_retries": 2})


# --------------------------------------------------------------------------
# chaos bench contract
# --------------------------------------------------------------------------


def test_chaos_bench_contract(tmp_path, capsys):
    import json
    import sys

    sys.path.insert(0, "scripts")
    import chaos_bench

    out = tmp_path / "chaos.json"
    rc = chaos_bench.main([
        "--requests", "16", "--concurrency", "4", "--fault-p", "0.15",
        "--hang-s", "0.3", "--watchdog-s", "0.1", "--max-retries", "3",
        "--min-availability", "0", "--out", str(out),
    ])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "chaos_availability"
    assert rec["scheduler_alive"] is True
    assert rec["poison_shed_max_s"] is not None
    assert rec["poison_shed_max_s"] < 1.0
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["poison"]["shed_count"] > 0
    assert art["poison"]["healthy_bucket_survived"]
    assert art["mixed"]["health"]["scheduler_alive"]

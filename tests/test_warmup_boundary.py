"""Pin the warmup phase boundary (reference: counter <= warmup_steps selects
the synchronous path everywhere, SURVEY.md §2.3).

With num_steps = warmup+1 the displaced modes never reach the stale phase, so
they must match full_sync bit-for-bit; with one more step the first stale
step runs and outputs must diverge.
"""

import jax
import numpy as np

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.parallel.runner import DenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
import pytest


def _run(devices8, mode, steps, warmup):
    cfg = DistriConfig(devices=devices8[:4], height=128, width=128,
                       warmup_steps=warmup, mode=mode)
    ucfg = tiny_config()
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    k = jax.random.PRNGKey(7)
    lat = jax.random.normal(k, (1, 16, 16, 4))
    enc = jax.random.normal(jax.random.fold_in(k, 1), (2, 1, 7, ucfg.cross_attention_dim))
    return np.asarray(runner.generate(lat, enc, num_inference_steps=steps))


def test_warmup_plus_one_is_fully_synchronous(devices8):
    w = 2
    a = _run(devices8, "corrected_async_gn", w + 1, w)
    b = _run(devices8, "full_sync", w + 1, w)
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_first_stale_step_diverges(devices8):
    w = 2
    a = _run(devices8, "corrected_async_gn", w + 2, w)
    b = _run(devices8, "full_sync", w + 2, w)
    assert np.abs(a - b).max() > 1e-6, (
        "displaced mode never engaged the stale path"
    )


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

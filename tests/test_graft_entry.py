"""The driver's gate functions must keep working: entry() compiles and runs,
dryrun_multichip exercises the full multi-parallelism step on the fake mesh."""

import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_entry_tiny_compiles(monkeypatch):
    monkeypatch.setenv("DISTRIFUSER_TPU_GRAFT_PRESET", "tiny")
    # entry()/dryrun setdefault DISTRIFUSER_TPU_FLASH=0 process-wide (the
    # driver gate wants the XLA path on CPU); pre-setting it via monkeypatch
    # makes that mutation test-scoped instead of leaking into later files
    monkeypatch.setenv("DISTRIFUSER_TPU_FLASH", "0")
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == 1 and out.shape[-1] == 4
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow  # compiles patch + tensor + dp loops on the fake
# 8-device mesh — minutes on the 2-core tier-1 CPU runner
def test_dryrun_multichip_8(monkeypatch):
    monkeypatch.setenv("DISTRIFUSER_TPU_GRAFT_PRESET", "tiny")
    monkeypatch.setenv("DISTRIFUSER_TPU_FLASH", "0")  # see above
    import __graft_entry__ as g

    g.dryrun_multichip(8)  # patch + tensor + dp over the 3-axis mesh

"""Whole-UNet torch parity: the strongest no-real-weights validation.

tests/test_torch_parity.py pins per-op numerics and
test_torch_parity_blocks.py pins block composition; this pins the ENTIRE
UNet2DConditionModel graph — skip-connection push/pop order, down/upsample
placement between blocks, time + SDXL added-cond embedding injection — by
assembling the full torch reference (tests/torch_ref.py) with diffusers
state_dict naming, converting its weights through the real
convert_unet_state_dict, and requiring unet_forward to reproduce the torch
output.  A conversion or composition bug anywhere in the model cannot pass
this while staying shape-correct.
"""

import numpy as np
import pytest
import torch

from distrifuser_tpu.models.unet import tiny_config, unet_forward
from distrifuser_tpu.models.weights import convert_unet_state_dict

from torch_ref import TorchUNet


@pytest.mark.parametrize("sdxl", [False, True])
def test_full_unet_matches_torch(sdxl):
    cfg = tiny_config(sdxl=sdxl)
    torch.manual_seed(0)
    ref = TorchUNet(cfg).eval()
    # non-trivial norm affines so identity-affine conversion bugs can't hide
    with torch.no_grad():
        for m in ref.modules():
            if isinstance(m, (torch.nn.GroupNorm, torch.nn.LayerNorm)):
                m.weight.mul_(torch.randn_like(m.weight) * 0.2 + 1.0)
                m.bias.add_(torch.randn_like(m.bias) * 0.3)

    params = convert_unet_state_dict(
        {k: v.detach().numpy() for k, v in ref.state_dict().items()}
    )

    b, size = 2, 16
    x = torch.randn(b, cfg.in_channels, size, size)
    t = torch.tensor([500.0, 10.0])
    enc = torch.randn(b, 7, cfg.cross_attention_dim)
    added_t = added_j = None
    if sdxl:
        emb = cfg.projection_class_embeddings_input_dim - 6 * cfg.addition_time_embed_dim
        text_embeds = torch.randn(b, emb)
        time_ids = torch.tensor([[64.0, 64, 0, 0, 64, 64]] * b)
        added_t = {"text_embeds": text_embeds, "time_ids": time_ids}
        added_j = {
            "text_embeds": np.asarray(text_embeds),
            "time_ids": np.asarray(time_ids),
        }

    with torch.no_grad():
        y_t = ref(x, t, enc, added_cond=added_t)

    y_j = unet_forward(
        params, cfg, np.asarray(x.permute(0, 2, 3, 1).contiguous()),
        np.asarray(t), np.asarray(enc), added_cond=added_j,
    )
    np.testing.assert_allclose(
        np.moveaxis(np.asarray(y_j), 3, 1), y_t.numpy(), rtol=5e-4, atol=5e-4
    )

"""Displaced patch parallelism on the MMDiT (parallel/mmdit_sp.py).

Oracle: per-patch sequential evaluation with per-block gathered image-KV
caches — stale step s attends jointly over concat(context KV, cache with
the patch's own rows fresh), exactly the runner's assembly.  The context
stream restarts from ctx0 every evaluation and, in the stale phase, sees
each patch's own-fresh view of the image KV (the displaced approximation
extends to the context stream by construction — pinned here so the choice
cannot drift silently).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distrifuser_tpu.models import dit as dit_mod
from distrifuser_tpu.models import mmdit as mm
from distrifuser_tpu.ops.attention import sdpa
from distrifuser_tpu.ops.linear import linear
from distrifuser_tpu.parallel.mmdit_sp import MMDiTDenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.utils.config import DistriConfig


def make_model():
    mcfg = mm.tiny_mmdit_config()
    params = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)
    return mcfg, params


def make_inputs(mcfg, batch=1, lc=5):
    k = jax.random.PRNGKey(7)
    lat = jax.random.normal(
        k, (batch, mcfg.sample_size, mcfg.sample_size, mcfg.in_channels)
    )
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, batch, lc, mcfg.joint_attention_dim)
    )
    pooled = jax.random.normal(
        jax.random.fold_in(k, 2), (2, batch, mcfg.pooled_projection_dim)
    )
    return lat, enc, pooled


def dense_loop(params, mcfg, sched, lat, enc, pooled, gs, num_steps,
               do_cfg=True):
    """Single-device reference: full mmdit_forward per branch per step."""
    sched.set_timesteps(num_steps)
    ts = sched.timesteps()
    x = lat.astype(jnp.float32)
    sstate = sched.init_state(x.shape)
    branches = (0, 1) if do_cfg else (0,)
    for s in range(num_steps):
        x_in = sched.scale_model_input(x, s)
        outs = {
            br: mm.mmdit_forward(params, mcfg, x_in, ts[s], enc[br],
                                 pooled[br])
            for br in branches
        }
        v = (outs[0] + gs * (outs[1] - outs[0])) if do_cfg else outs[0]
        x, sstate = sched.step(x, v.astype(jnp.float32), s, sstate)
    return x


def oracle_displaced(params, mcfg, sched, lat, enc, pooled, gs, num_steps,
                     warmup_steps, n, do_cfg=True, refresh=True):
    sched.set_timesteps(num_steps)
    ts = sched.timesteps()
    x = dit_mod.patchify(mcfg, lat.astype(jnp.float32))
    batch, n_tok, _ = x.shape
    chunk = n_tok // n
    n_sync = min(warmup_steps + 1, num_steps)
    hid = mcfg.hidden_size
    pos = mm.pos_embed_cropped(mcfg, jnp.float32)
    branches = (0, 1) if do_cfg else (0,)

    ctx0 = {br: linear(params["ctx_in"], enc[br]) for br in branches}
    cache = {br: [(jnp.zeros((batch, n_tok, hid)),
                   jnp.zeros((batch, n_tok, hid)))
                  for _ in range(mcfg.depth)] for br in branches}
    sstate = sched.init_state(x.shape)

    def run_stack(br, tokens, s, sync, offset):
        vec = mm.cond_vec(params, mcfg, ts[s], pooled[br])
        pos_rows = jax.lax.dynamic_slice_in_dim(pos, offset, tokens.shape[1], 0)
        h = linear(params["proj_in"], tokens) + pos_rows[None]
        ctx = ctx0[br]
        fresh = []
        for l in range(mcfg.depth):
            bp = jax.tree.map(lambda a: a[l], params["blocks"])

            def assemble(k, v, l=l):
                if sync:
                    return k, v
                ck, cv = cache[br][l]
                return (
                    jax.lax.dynamic_update_slice(ck, k, (0, offset, 0)),
                    jax.lax.dynamic_update_slice(cv, v, (0, offset, 0)),
                )

            h, ctx, (k, v) = mm.mmdit_block(bp, mcfg, h, ctx, vec,
                                            kv_assemble=assemble)
            fresh.append((k, v))
        return mm.final_layer(params, mcfg, h, vec), fresh

    def combine(out):
        if not do_cfg:
            return out[0]
        return out[0] + gs * (out[1] - out[0])

    for s in range(num_steps):
        x_in = sched.scale_model_input(x, s)
        if s < n_sync:
            out, fr = {}, {}
            for br in branches:
                out[br], fr[br] = run_stack(br, x_in, s, True, 0)
                cache[br] = fr[br]
        else:
            out = {br: [] for br in branches}
            fresh_all = {br: [[] for _ in range(mcfg.depth)]
                         for br in branches}
            for p in range(n):
                rows = x_in[:, p * chunk:(p + 1) * chunk]
                for br in branches:
                    e, fr = run_stack(br, rows, s, False, p * chunk)
                    out[br].append(e)
                    for l in range(mcfg.depth):
                        fresh_all[br][l].append(fr[l])
            out = {br: jnp.concatenate(v, axis=1) for br, v in out.items()}
            if refresh:
                for br in branches:
                    cache[br] = [
                        (jnp.concatenate([kv[0] for kv in fresh_all[br][l]],
                                         axis=1),
                         jnp.concatenate([kv[1] for kv in fresh_all[br][l]],
                                         axis=1))
                        for l in range(mcfg.depth)
                    ]
        x, sstate = sched.step(x, combine(out).astype(jnp.float32), s, sstate)

    return dit_mod.unpatchify(mcfg, x, mcfg.out_channels)


def sp_config(n_dev, do_cfg, **kw):
    return DistriConfig(
        devices=jax.devices()[:n_dev], height=256, width=256,
        do_classifier_free_guidance=do_cfg, split_batch=do_cfg, **kw,
    )


def test_full_sync_matches_dense():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, mode="full_sync")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=3)
    ref = dense_loop(params, mcfg, get_scheduler("flow-euler"), lat, enc,
                     pooled, 1.0, 3, do_cfg=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_displaced_matches_oracle():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, warmup_steps=1)
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=6)
    ref = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_cfg_split_composes():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(8, do_cfg=True, warmup_steps=1)
    assert cfg.cfg_split and cfg.n_device_per_batch == 4
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=4.0,
                          num_inference_steps=5)
    ref = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 4.0, 5,
        warmup_steps=1, n=4, do_cfg=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_cfg_folded():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = DistriConfig(
        devices=jax.devices()[:2], height=256, width=256,
        do_classifier_free_guidance=True, split_batch=False, warmup_steps=1,
    )
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=4.0,
                          num_inference_steps=4)
    ref = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 4.0, 4,
        warmup_steps=1, n=2, do_cfg=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_no_sync_mode():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, warmup_steps=1, mode="no_sync")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=6)
    ref = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False, refresh=False,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    ref_refresh = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False, refresh=True,
    )
    assert not np.allclose(np.asarray(out), np.asarray(ref_refresh),
                           rtol=2e-4, atol=2e-4)


def test_rejected_knobs_and_geometry():
    mcfg, params = make_model()
    with pytest.raises(ValueError, match="gather"):
        MMDiTDenoiseRunner(sp_config(4, do_cfg=False, attn_impl="ring"),
                           mcfg, params, get_scheduler("flow-euler"))
    with pytest.raises(ValueError, match="comm_batch"):
        MMDiTDenoiseRunner(sp_config(4, do_cfg=False, comm_batch=True),
                           mcfg, params, get_scheduler("flow-euler"))
    with pytest.raises(ValueError, match="sample_size"):
        MMDiTDenoiseRunner(
            DistriConfig(devices=jax.devices()[:2], height=128, width=128),
            mcfg, params, get_scheduler("flow-euler"))


def test_comm_report():
    mcfg, params = make_model()
    cfg = sp_config(4, do_cfg=False, warmup_steps=1)
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    rep = runner.comm_report()
    assert rep["layout"] == "gather"
    assert rep["kv_state_elems"] == (
        mcfg.depth * 2 * mcfg.num_tokens * mcfg.hidden_size
    )
    assert rep["per_step_collective_elems"] > rep["kv_state_elems"]


def test_ring_matches_gather():
    """attn_impl='ring': O(L/n) state + static context block, same displaced
    numerics as 'gather' (online vs plain softmax differ only in
    rounding)."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    kw = dict(guidance_scale=1.0, num_inference_steps=5)
    outs = {}
    for impl in ("gather", "ring"):
        cfg = sp_config(4, do_cfg=False, warmup_steps=1, attn_impl=impl)
        runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                    get_scheduler("flow-euler"))
        outs[impl] = np.asarray(runner.generate(lat, enc, pooled, **kw))
    np.testing.assert_allclose(outs["ring"], outs["gather"],
                               rtol=2e-4, atol=2e-4)


def test_ring_full_sync_matches_dense():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, mode="full_sync", attn_impl="ring")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=3)
    ref = dense_loop(params, mcfg, get_scheduler("flow-euler"), lat, enc,
                     pooled, 1.0, 3, do_cfg=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_no_sync_matches_gather_no_sync():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    kw = dict(guidance_scale=1.0, num_inference_steps=5)
    outs = {}
    for impl in ("gather", "ring"):
        cfg = sp_config(4, do_cfg=False, warmup_steps=1, mode="no_sync",
                        attn_impl=impl)
        runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                    get_scheduler("flow-euler"))
        outs[impl] = np.asarray(runner.generate(lat, enc, pooled, **kw))
    np.testing.assert_allclose(outs["ring"], outs["gather"],
                               rtol=2e-4, atol=2e-4)


def test_ring_comm_report():
    mcfg, params = make_model()
    cfg = sp_config(4, do_cfg=False, warmup_steps=1, attn_impl="ring")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    rep = runner.comm_report()
    assert rep["layout"] == "ring"
    chunk = mcfg.num_tokens // 4
    assert rep["kv_state_elems"] == mcfg.depth * chunk * 2 * mcfg.hidden_size
    gather = MMDiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=1), mcfg, params,
        get_scheduler("flow-euler"),
    ).comm_report()
    # gather carries all n chunks; ring only the own one
    assert rep["kv_state_elems"] * 4 == gather["kv_state_elems"]


def test_start_step_matches_offset_dense():
    """img2img entry (start_step > 0): the fused loop's offsets replay the
    per-step schedule exactly, warmup counted from the first executed
    step."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)

    def dense_from(start, num):
        sched = get_scheduler("flow-euler").set_timesteps(num)
        ts = sched.timesteps()
        x = lat.astype(jnp.float32)
        ss = sched.init_state(x.shape)
        for s in range(start, num):
            v = mm.mmdit_forward(params, mcfg, sched.scale_model_input(x, s),
                                 ts[s], enc[0], pooled[0])
            x, ss = sched.step(x, v.astype(jnp.float32), s, ss)
        return np.asarray(x)

    cfg = sp_config(4, do_cfg=False, mode="full_sync")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    for start in (2, 4):
        out = np.asarray(runner.generate(
            lat, enc, pooled, guidance_scale=1.0, num_inference_steps=5,
            start_step=start,
        ))
        np.testing.assert_allclose(out, dense_from(start, 5),
                                   rtol=2e-4, atol=2e-4)
    # displaced path with an offset runs and the offset engages
    cfg_d = sp_config(4, do_cfg=False, warmup_steps=1)
    runner_d = MMDiTDenoiseRunner(cfg_d, mcfg, params,
                                  get_scheduler("flow-euler"))
    full = np.asarray(runner_d.generate(lat, enc, pooled, guidance_scale=1.0,
                                        num_inference_steps=5))
    tail = np.asarray(runner_d.generate(lat, enc, pooled, guidance_scale=1.0,
                                        num_inference_steps=5, start_step=3))
    assert np.abs(full - tail).max() > 0
    with pytest.raises(AssertionError):
        runner_d.generate(lat, enc, pooled, num_inference_steps=4,
                          start_step=4)

"""Displaced patch parallelism on the MMDiT (parallel/mmdit_sp.py).

Oracle: per-patch sequential evaluation with per-block gathered image-KV
caches — stale step s attends jointly over concat(context KV, cache with
the patch's own rows fresh), exactly the runner's assembly.  The context
stream restarts from ctx0 every evaluation and, in the stale phase, sees
each patch's own-fresh view of the image KV (the displaced approximation
extends to the context stream by construction — pinned here so the choice
cannot drift silently).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distrifuser_tpu.models import dit as dit_mod
from distrifuser_tpu.models import mmdit as mm
from distrifuser_tpu.ops.attention import sdpa
from distrifuser_tpu.ops.linear import linear
from distrifuser_tpu.parallel.mmdit_sp import MMDiTDenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.utils.config import DistriConfig


def make_model():
    mcfg = mm.tiny_mmdit_config()
    params = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)
    return mcfg, params


def make_inputs(mcfg, batch=1, lc=5):
    k = jax.random.PRNGKey(7)
    lat = jax.random.normal(
        k, (batch, mcfg.sample_size, mcfg.sample_size, mcfg.in_channels)
    )
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, batch, lc, mcfg.joint_attention_dim)
    )
    pooled = jax.random.normal(
        jax.random.fold_in(k, 2), (2, batch, mcfg.pooled_projection_dim)
    )
    return lat, enc, pooled


def dense_loop(params, mcfg, sched, lat, enc, pooled, gs, num_steps,
               do_cfg=True):
    """Single-device reference: full mmdit_forward per branch per step."""
    sched.set_timesteps(num_steps)
    ts = sched.timesteps()
    x = lat.astype(jnp.float32)
    sstate = sched.init_state(x.shape)
    branches = (0, 1) if do_cfg else (0,)
    for s in range(num_steps):
        x_in = sched.scale_model_input(x, s)
        outs = {
            br: mm.mmdit_forward(params, mcfg, x_in, ts[s], enc[br],
                                 pooled[br])
            for br in branches
        }
        v = (outs[0] + gs * (outs[1] - outs[0])) if do_cfg else outs[0]
        x, sstate = sched.step(x, v.astype(jnp.float32), s, sstate)
    return x


def oracle_displaced(params, mcfg, sched, lat, enc, pooled, gs, num_steps,
                     warmup_steps, n, do_cfg=True, refresh=True):
    sched.set_timesteps(num_steps)
    ts = sched.timesteps()
    x = dit_mod.patchify(mcfg, lat.astype(jnp.float32))
    batch, n_tok, _ = x.shape
    chunk = n_tok // n
    n_sync = min(warmup_steps + 1, num_steps)
    hid = mcfg.hidden_size
    pos = mm.pos_embed_cropped(mcfg, jnp.float32)
    branches = (0, 1) if do_cfg else (0,)

    ctx0 = {br: linear(params["ctx_in"], enc[br]) for br in branches}
    cache = {br: [(jnp.zeros((batch, n_tok, hid)),
                   jnp.zeros((batch, n_tok, hid)))
                  for _ in range(mcfg.depth)] for br in branches}
    sstate = sched.init_state(x.shape)

    def run_stack(br, tokens, s, sync, offset):
        vec = mm.cond_vec(params, mcfg, ts[s], pooled[br])
        pos_rows = jax.lax.dynamic_slice_in_dim(pos, offset, tokens.shape[1], 0)
        h = linear(params["proj_in"], tokens) + pos_rows[None]
        ctx = ctx0[br]
        fresh = []
        for l in range(mcfg.depth):
            bp = jax.tree.map(lambda a: a[l], params["blocks"])

            def assemble(k, v, l=l):
                if sync:
                    return k, v
                ck, cv = cache[br][l]
                return (
                    jax.lax.dynamic_update_slice(ck, k, (0, offset, 0)),
                    jax.lax.dynamic_update_slice(cv, v, (0, offset, 0)),
                )

            h, ctx, (k, v) = mm.mmdit_block(bp, mcfg, h, ctx, vec,
                                            kv_assemble=assemble)
            fresh.append((k, v))
        return mm.final_layer(params, mcfg, h, vec), fresh

    def combine(out):
        if not do_cfg:
            return out[0]
        return out[0] + gs * (out[1] - out[0])

    for s in range(num_steps):
        x_in = sched.scale_model_input(x, s)
        if s < n_sync:
            out, fr = {}, {}
            for br in branches:
                out[br], fr[br] = run_stack(br, x_in, s, True, 0)
                cache[br] = fr[br]
        else:
            out = {br: [] for br in branches}
            fresh_all = {br: [[] for _ in range(mcfg.depth)]
                         for br in branches}
            for p in range(n):
                rows = x_in[:, p * chunk:(p + 1) * chunk]
                for br in branches:
                    e, fr = run_stack(br, rows, s, False, p * chunk)
                    out[br].append(e)
                    for l in range(mcfg.depth):
                        fresh_all[br][l].append(fr[l])
            out = {br: jnp.concatenate(v, axis=1) for br, v in out.items()}
            if refresh:
                for br in branches:
                    cache[br] = [
                        (jnp.concatenate([kv[0] for kv in fresh_all[br][l]],
                                         axis=1),
                         jnp.concatenate([kv[1] for kv in fresh_all[br][l]],
                                         axis=1))
                        for l in range(mcfg.depth)
                    ]
        x, sstate = sched.step(x, combine(out).astype(jnp.float32), s, sstate)

    return dit_mod.unpatchify(mcfg, x, mcfg.out_channels)


def sp_config(n_dev, do_cfg, **kw):
    return DistriConfig(
        devices=jax.devices()[:n_dev], height=256, width=256,
        do_classifier_free_guidance=do_cfg, split_batch=do_cfg, **kw,
    )


def test_full_sync_matches_dense():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, mode="full_sync")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=3)
    ref = dense_loop(params, mcfg, get_scheduler("flow-euler"), lat, enc,
                     pooled, 1.0, 3, do_cfg=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_displaced_matches_oracle():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, warmup_steps=1)
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=6)
    ref = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_cfg_split_composes():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(8, do_cfg=True, warmup_steps=1)
    assert cfg.cfg_split and cfg.n_device_per_batch == 4
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=4.0,
                          num_inference_steps=5)
    ref = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 4.0, 5,
        warmup_steps=1, n=4, do_cfg=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_cfg_folded():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = DistriConfig(
        devices=jax.devices()[:2], height=256, width=256,
        do_classifier_free_guidance=True, split_batch=False, warmup_steps=1,
    )
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=4.0,
                          num_inference_steps=4)
    ref = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 4.0, 4,
        warmup_steps=1, n=2, do_cfg=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_no_sync_mode():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, warmup_steps=1, mode="no_sync")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=6)
    ref = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False, refresh=False,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    ref_refresh = oracle_displaced(
        params, mcfg, get_scheduler("flow-euler"), lat, enc, pooled, 1.0, 6,
        warmup_steps=1, n=4, do_cfg=False, refresh=True,
    )
    assert not np.allclose(np.asarray(out), np.asarray(ref_refresh),
                           rtol=2e-4, atol=2e-4)


def test_rejected_knobs_and_geometry():
    mcfg, params = make_model()
    # head-sharding layouts are undefined for joint attention's two-origin
    # queries; ring/gather are the supported pair
    with pytest.raises(ValueError, match="two-origin"):
        MMDiTDenoiseRunner(sp_config(4, do_cfg=False, attn_impl="ulysses"),
                           mcfg, params, get_scheduler("flow-euler"))
    with pytest.raises(ValueError, match="comm_batch"):
        MMDiTDenoiseRunner(sp_config(4, do_cfg=False, comm_batch=True),
                           mcfg, params, get_scheduler("flow-euler"))
    with pytest.raises(ValueError, match="sample_size"):
        MMDiTDenoiseRunner(
            DistriConfig(devices=jax.devices()[:2], height=128, width=128),
            mcfg, params, get_scheduler("flow-euler"))


def test_comm_report():
    mcfg, params = make_model()
    cfg = sp_config(4, do_cfg=False, warmup_steps=1)
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    rep = runner.comm_report()
    assert rep["layout"] == "gather"
    assert rep["kv_state_elems"] == (
        mcfg.depth * 2 * mcfg.num_tokens * mcfg.hidden_size
    )
    assert rep["per_step_collective_elems"] > rep["kv_state_elems"]


def test_ring_matches_gather():
    """attn_impl='ring': O(L/n) state + static context block, same displaced
    numerics as 'gather' (online vs plain softmax differ only in
    rounding)."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    kw = dict(guidance_scale=1.0, num_inference_steps=5)
    outs = {}
    for impl in ("gather", "ring"):
        cfg = sp_config(4, do_cfg=False, warmup_steps=1, attn_impl=impl)
        runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                    get_scheduler("flow-euler"))
        outs[impl] = np.asarray(runner.generate(lat, enc, pooled, **kw))
    np.testing.assert_allclose(outs["ring"], outs["gather"],
                               rtol=2e-4, atol=2e-4)


def test_ring_full_sync_matches_dense():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    cfg = sp_config(4, do_cfg=False, mode="full_sync", attn_impl="ring")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    out = runner.generate(lat, enc, pooled, guidance_scale=1.0,
                          num_inference_steps=3)
    ref = dense_loop(params, mcfg, get_scheduler("flow-euler"), lat, enc,
                     pooled, 1.0, 3, do_cfg=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ring_no_sync_matches_gather_no_sync():
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    kw = dict(guidance_scale=1.0, num_inference_steps=5)
    outs = {}
    for impl in ("gather", "ring"):
        cfg = sp_config(4, do_cfg=False, warmup_steps=1, mode="no_sync",
                        attn_impl=impl)
        runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                    get_scheduler("flow-euler"))
        outs[impl] = np.asarray(runner.generate(lat, enc, pooled, **kw))
    np.testing.assert_allclose(outs["ring"], outs["gather"],
                               rtol=2e-4, atol=2e-4)


def test_ring_comm_report():
    mcfg, params = make_model()
    cfg = sp_config(4, do_cfg=False, warmup_steps=1, attn_impl="ring")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    rep = runner.comm_report()
    assert rep["layout"] == "ring"
    chunk = mcfg.num_tokens // 4
    assert rep["kv_state_elems"] == mcfg.depth * chunk * 2 * mcfg.hidden_size
    gather = MMDiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=1), mcfg, params,
        get_scheduler("flow-euler"),
    ).comm_report()
    # gather carries all n chunks; ring only the own one
    assert rep["kv_state_elems"] * 4 == gather["kv_state_elems"]


def test_start_step_matches_offset_dense():
    """img2img entry (start_step > 0): the fused loop's offsets replay the
    per-step schedule exactly, warmup counted from the first executed
    step."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)

    def dense_from(start, num):
        sched = get_scheduler("flow-euler").set_timesteps(num)
        ts = sched.timesteps()
        x = lat.astype(jnp.float32)
        ss = sched.init_state(x.shape)
        for s in range(start, num):
            v = mm.mmdit_forward(params, mcfg, sched.scale_model_input(x, s),
                                 ts[s], enc[0], pooled[0])
            x, ss = sched.step(x, v.astype(jnp.float32), s, ss)
        return np.asarray(x)

    cfg = sp_config(4, do_cfg=False, mode="full_sync")
    runner = MMDiTDenoiseRunner(cfg, mcfg, params,
                                get_scheduler("flow-euler"))
    for start in (2, 4):
        out = np.asarray(runner.generate(
            lat, enc, pooled, guidance_scale=1.0, num_inference_steps=5,
            start_step=start,
        ))
        np.testing.assert_allclose(out, dense_from(start, 5),
                                   rtol=2e-4, atol=2e-4)
    # displaced path with an offset runs and the offset engages
    cfg_d = sp_config(4, do_cfg=False, warmup_steps=1)
    runner_d = MMDiTDenoiseRunner(cfg_d, mcfg, params,
                                  get_scheduler("flow-euler"))
    full = np.asarray(runner_d.generate(lat, enc, pooled, guidance_scale=1.0,
                                        num_inference_steps=5))
    tail = np.asarray(runner_d.generate(lat, enc, pooled, guidance_scale=1.0,
                                        num_inference_steps=5, start_step=3))
    assert np.abs(full - tail).max() > 0
    with pytest.raises(AssertionError):
        runner_d.generate(lat, enc, pooled, num_inference_steps=4,
                          start_step=4)


def test_stepwise_matches_fused():
    """use_cuda_graph=False parity for the MMDiT runner: the host-driven
    per-step programs equal the fused loop in displaced, ring, and
    full_sync configs."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    kw = dict(guidance_scale=1.0, num_inference_steps=4)
    for extra in ({}, {"attn_impl": "ring"}, {"mode": "full_sync"}):
        fused = MMDiTDenoiseRunner(
            sp_config(4, do_cfg=False, warmup_steps=1, **extra),
            mcfg, params, get_scheduler("flow-euler"))
        stepw = MMDiTDenoiseRunner(
            sp_config(4, do_cfg=False, warmup_steps=1, use_cuda_graph=False,
                      **extra),
            mcfg, params, get_scheduler("flow-euler"))
        a = np.asarray(fused.generate(lat, enc, pooled, **kw))
        b = np.asarray(stepw.generate(lat, enc, pooled, **kw))
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4,
                                   err_msg=str(extra))


def test_callback_all_modes():
    """The diffusers legacy callback fires with identical count, order,
    timesteps, and latents from the host loop and from inside the
    compiled loop (ordered io_callback)."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)

    def run(runner, **kw):
        seen = []
        out = runner.generate(
            lat, enc, pooled, guidance_scale=1.0, num_inference_steps=4,
            callback=lambda i, t, x: seen.append(
                (int(i), float(t), np.array(x, copy=True))),
            **kw,
        )
        return seen, np.asarray(out)

    stepw = MMDiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=1, use_cuda_graph=False),
        mcfg, params, get_scheduler("flow-euler"))
    fused = MMDiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=1),
        mcfg, params, get_scheduler("flow-euler"))
    s_seen, s_out = run(stepw)
    f_seen, f_out = run(fused)
    assert [i for i, _, _ in s_seen] == [0, 1, 2, 3]
    assert [i for i, _, _ in f_seen] == [i for i, _, _ in s_seen]
    assert [t for _, t, _ in f_seen] == [t for _, t, _ in s_seen]
    for (_, _, xa), (_, _, xb) in zip(f_seen, s_seen):
        np.testing.assert_allclose(xa, xb, atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(f_out, s_out, atol=2e-4, rtol=2e-4)
    # the last callback sees exactly the returned latents
    np.testing.assert_allclose(f_seen[-1][2], f_out, atol=0)
    # img2img window: callbacks start at start_step
    o_seen, _ = run(fused, start_step=2)
    assert [i for i, _, _ in o_seen] == [2, 3]


def test_stepwise_retables_on_step_count_change():
    """A second stepwise generate with a DIFFERENT step count must not
    reuse the first call's baked scheduler tables (code-review r5: the
    stepwise cache is keyed by num_steps)."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    stepw = MMDiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=1, use_cuda_graph=False),
        mcfg, params, get_scheduler("flow-euler"))
    fused = MMDiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=1),
        mcfg, params, get_scheduler("flow-euler"))
    kw = dict(guidance_scale=1.0)
    stepw.generate(lat, enc, pooled, num_inference_steps=3, **kw)  # bake 3
    b = np.asarray(stepw.generate(lat, enc, pooled, num_inference_steps=6,
                                  **kw))
    a = np.asarray(fused.generate(lat, enc, pooled, num_inference_steps=6,
                                  **kw))
    np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)


def test_hybrid_matches_fused():
    """hybrid_loop (per-step sync warmup + fused stale-only scan) equals
    the fully fused loop for both KV layouts — the compile-time-resilient
    execution of the same program, completing the knob across all four
    runners."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    kw = dict(guidance_scale=1.0, num_inference_steps=5)
    for extra in ({}, {"attn_impl": "ring"}):
        fused = MMDiTDenoiseRunner(
            sp_config(4, do_cfg=False, warmup_steps=1, **extra),
            mcfg, params, get_scheduler("flow-euler"))
        hybrid = MMDiTDenoiseRunner(
            sp_config(4, do_cfg=False, warmup_steps=1, hybrid_loop=True,
                      **extra),
            mcfg, params, get_scheduler("flow-euler"))
        hybrid.prepare(5)  # the pre-built program is what dispatches
        a = np.asarray(fused.generate(lat, enc, pooled, **kw))
        b = np.asarray(hybrid.generate(lat, enc, pooled, **kw))
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4,
                                   err_msg=str(extra))
    # all-sync short runs fall back to the fused path inside hybrid
    hybrid2 = MMDiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=3, hybrid_loop=True),
        mcfg, params, get_scheduler("flow-euler"))
    fused2 = MMDiTDenoiseRunner(
        sp_config(4, do_cfg=False, warmup_steps=3),
        mcfg, params, get_scheduler("flow-euler"))
    a2 = np.asarray(fused2.generate(lat, enc, pooled, guidance_scale=1.0,
                                    num_inference_steps=2))
    b2 = np.asarray(hybrid2.generate(lat, enc, pooled, guidance_scale=1.0,
                                     num_inference_steps=2))
    np.testing.assert_allclose(a2, b2, atol=2e-4, rtol=2e-4)


def test_stepwise_cfg_modes_match_fused():
    """The stepwise boundary adds CFG-dependent machinery the fused path
    never had (_kv0_global branch doubling, CFG_AXIS in the kv spec) —
    pin folded-CFG and cfg_split stepwise against their fused twins
    (code-review r5)."""
    mcfg, params = make_model()
    lat, enc, pooled = make_inputs(mcfg)
    kw = dict(guidance_scale=4.0, num_inference_steps=3)
    configs = [
        # folded CFG: both branches ride the batch dim (bloc doubling)
        dict(devices=jax.devices()[:2], height=256, width=256,
             do_classifier_free_guidance=True, split_batch=False,
             warmup_steps=1),
        # cfg_split: one branch per device group (CFG_AXIS in kv_spec)
        dict(devices=jax.devices()[:8], height=256, width=256,
             do_classifier_free_guidance=True, split_batch=True,
             warmup_steps=1),
    ]
    for ckw in configs:
        fused = MMDiTDenoiseRunner(DistriConfig(**ckw), mcfg, params,
                                   get_scheduler("flow-euler"))
        stepw = MMDiTDenoiseRunner(
            DistriConfig(use_cuda_graph=False, **ckw), mcfg, params,
            get_scheduler("flow-euler"))
        a = np.asarray(fused.generate(lat, enc, pooled, **kw))
        b = np.asarray(stepw.generate(lat, enc, pooled, **kw))
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4,
                                   err_msg=str(ckw["split_batch"]))


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

"""Patch-parallel ops vs their dense oracles on the fake 8-device mesh.

The tests the reference never had (SURVEY.md §4): each distributed op, run
under shard_map in sync phase, must reproduce the dense op on the full image
exactly (up to reduction order); stale-phase semantics are checked against
hand-computed displaced values.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from distrifuser_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distrifuser_tpu.ops import (
    attention,
    conv2d,
    group_norm,
    patch_conv2d,
    patch_self_attention,
    patch_group_norm,
    sliced_conv2d,
)
from distrifuser_tpu.parallel.context import PHASE_STALE, PHASE_SYNC, PatchContext
from distrifuser_tpu.utils.config import SP_AXIS


def sp_mesh(devices, n):
    return Mesh(np.array(devices[:n]).reshape(n), axis_names=(SP_AXIS,))


def conv_params(key, kh, kw, cin, cout):
    k1, k2 = jax.random.split(key)
    return {
        "kernel": jax.random.normal(k1, (kh, kw, cin, cout), jnp.float32) * 0.2,
        "bias": jax.random.normal(k2, (cout,), jnp.float32) * 0.1,
    }


def run_patch_op(mesh, fn, x, state=None, n=None, mode="corrected_async_gn", phase=PHASE_SYNC):
    """Run `fn(x_local, ctx) -> y_local` under shard_map, returning (y, state_out)."""
    n = n or mesh.shape[SP_AXIS]

    def wrapped(xl, st):
        ctx = PatchContext(n=n, mode=mode, phase=phase, state_in=st)
        y = fn(xl, ctx)
        return y, ctx.state_out

    state_specs = None if state is None else jax.tree.map(lambda _: P(), state)
    return jax.jit(
        shard_map(
            wrapped,
            mesh=mesh,
            in_specs=(P(None, SP_AXIS), state_specs),
            out_specs=(P(None, SP_AXIS), jax.tree.map(lambda _: P(), state)
                       if state is not None else P()),
            check_vma=False,
        )
    )(x, state)


@pytest.mark.parametrize("n,stride,k", [(4, 1, 3), (4, 2, 3), (2, 1, 5), (8, 2, 3)])
def test_halo_conv_sync_matches_dense(devices8, n, stride, k):
    mesh = sp_mesh(devices8, n)
    key = jax.random.PRNGKey(0)
    b, h, w, cin, cout = 2, 16 * n // 2 * stride, 12, 3, 5
    # ensure h divisible by stride*n
    h = stride * n * 4
    x = jax.random.normal(key, (b, h, w, cin))
    p = conv_params(jax.random.PRNGKey(1), k, k, cin, cout)
    dense = conv2d(p, x, stride=stride)

    def fn(xl, ctx):
        return patch_conv2d(p, xl, ctx, "conv", stride=stride)

    def wrapped(xl):
        ctx = PatchContext(n=n, mode="full_sync", phase=PHASE_SYNC)
        return fn(xl, ctx)

    y = jax.jit(
        shard_map(wrapped, mesh=mesh, in_specs=P(None, SP_AXIS), out_specs=P(None, SP_AXIS))
    )(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), atol=1e-5)


@pytest.mark.parametrize("stride", [1, 2])
def test_sliced_conv_matches_dense(devices8, stride):
    n = 4
    mesh = sp_mesh(devices8, n)
    b, h, w, cin, cout = 1, stride * n * 4, 10, 4, 6
    x = jax.random.normal(jax.random.PRNGKey(2), (b, h, w, cin))
    p = conv_params(jax.random.PRNGKey(3), 3, 3, cin, cout)
    dense = conv2d(p, x, stride=stride)

    def wrapped(xf):
        ctx = PatchContext(n=n, mode="full_sync", phase=PHASE_SYNC)
        return sliced_conv2d(p, xf, ctx, stride=stride)

    y = jax.jit(
        shard_map(
            wrapped, mesh=mesh, in_specs=P(), out_specs=P(None, SP_AXIS), check_vma=False
        )
    )(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), atol=1e-5)


def test_halo_conv_stale_uses_previous_step(devices8):
    """Displaced semantics: step 2's conv must see step 1's neighbor rows."""
    n = 4
    mesh = sp_mesh(devices8, n)
    b, h, w, c = 1, 4 * n, 6, 2
    x1 = jax.random.normal(jax.random.PRNGKey(4), (b, h, w, c))
    x2 = jax.random.normal(jax.random.PRNGKey(5), (b, h, w, c))
    p = conv_params(jax.random.PRNGKey(6), 3, 3, c, c)

    def fn(xl, ctx):
        return patch_conv2d(p, xl, ctx, "conv")

    y1, state = run_patch_op(mesh, fn, x1, phase=PHASE_SYNC)
    y2, _ = run_patch_op(mesh, fn, x2, state=state, phase=PHASE_STALE)

    # Dense oracle for the stale step: each patch row-block convolved with
    # x2's interior but x1's rows at the patch boundaries.
    hp = h // n
    x2n, x1n = np.asarray(x2), np.asarray(x1)
    got = np.asarray(y2)
    for i in range(n):
        lo, hi = i * hp, (i + 1) * hp
        top = x1n[:, lo - 1 : lo] if i > 0 else np.zeros((b, 1, w, c), np.float32)
        bottom = x1n[:, hi : hi + 1] if i < n - 1 else np.zeros((b, 1, w, c), np.float32)
        padded = np.concatenate([top, x2n[:, lo:hi], bottom], axis=1)
        want = np.asarray(
            conv2d(p, jnp.asarray(padded), stride=1, padding=(0, 1))
        )
        np.testing.assert_allclose(got[:, lo:hi], want, atol=1e-5)


@pytest.mark.parametrize("mode", ["full_sync", "sync_gn", "stale_gn", "corrected_async_gn", "separate_gn", "no_sync"])
def test_group_norm_sync_phase_matches_global_moments(devices8, mode):
    """In the sync (warmup) phase every mode must use global moments + local-ne
    Bessel (groupnorm.py:45-47,74-91)."""
    n, b, h, w, c, g = 4, 2, 8, 6, 8, 4
    mesh = sp_mesh(devices8, n)
    x = jax.random.normal(jax.random.PRNGKey(7), (b, h * n, w, c)) * 2 + 1
    p = {
        "scale": jax.random.normal(jax.random.PRNGKey(8), (c,)) + 1,
        "bias": jax.random.normal(jax.random.PRNGKey(9), (c,)),
    }

    def fn(xl, ctx):
        return patch_group_norm(p, xl, ctx, "gn", groups=g)

    y, _ = run_patch_op(mesh, fn, x, mode=mode, phase=PHASE_SYNC)

    # dense oracle: global moments, Bessel with local ne
    xn = np.asarray(x, np.float64).reshape(b, n * h, w, g, c // g)
    mean = xn.mean(axis=(1, 2, 4), keepdims=True)
    var = (xn**2).mean(axis=(1, 2, 4), keepdims=True) - mean**2
    ne = (c // g) * h * w
    var = var * ne / (ne - 1)
    want = (xn - mean) / np.sqrt(var + 1e-5)
    want = want.reshape(b, n * h, w, c) * np.asarray(p["scale"]) + np.asarray(p["bias"])
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-4)


def test_group_norm_separate_steady_is_local(devices8):
    n, b, h, w, c, g = 4, 1, 6, 4, 4, 2
    mesh = sp_mesh(devices8, n)
    x = jax.random.normal(jax.random.PRNGKey(10), (b, h * n, w, c))
    p = {"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))}

    def fn(xl, ctx):
        return patch_group_norm(p, xl, ctx, "gn", groups=g)

    y, _ = run_patch_op(mesh, fn, x, mode="separate_gn", phase=PHASE_STALE)
    # oracle: plain (biased) GN applied per local patch
    want = np.concatenate(
        [
            np.asarray(group_norm(p, x[:, i * h : (i + 1) * h], groups=g))
            for i in range(n)
        ],
        axis=1,
    )
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-4)


def test_group_norm_stale_modes_displaced_semantics(devices8):
    """stale_gn: mean = (stale peers + fresh self)/n; corrected_async_gn adds the
    un-normalized correction and clamps negative variance to local."""
    n, b, h, w, c, g = 2, 1, 4, 4, 4, 2
    mesh = sp_mesh(devices8, n)
    x1 = jax.random.normal(jax.random.PRNGKey(11), (b, h * n, w, c))
    x2 = jax.random.normal(jax.random.PRNGKey(12), (b, h * n, w, c)) * 1.5

    p = None  # no affine

    def fn(xl, ctx):
        return patch_group_norm(p, xl, ctx, "gn", groups=g)

    def moments(xp):  # [2, B, G] for one patch
        xg = np.asarray(xp, np.float64).reshape(b, h, w, g, c // g)
        return np.stack([xg.mean(axis=(1, 2, 4)), (xg**2).mean(axis=(1, 2, 4))])

    for mode in ["stale_gn", "corrected_async_gn"]:
        _, state = run_patch_op(mesh, fn, x1, mode=mode, phase=PHASE_SYNC)
        y2, state2 = run_patch_op(mesh, fn, x2, state=state, mode=mode, phase=PHASE_STALE)

        ne = (c // g) * h * w
        got = np.asarray(y2)
        for i in range(n):
            m_fresh = moments(np.asarray(x2)[:, i * h : (i + 1) * h])
            stale_all = [moments(np.asarray(x1)[:, j * h : (j + 1) * h]) for j in range(n)]
            if mode == "stale_gn":
                full = (sum(stale_all) - stale_all[i] + m_fresh) / n
            else:
                full = sum(stale_all) / n + (m_fresh - stale_all[i])
            var = full[1] - full[0] ** 2
            if mode == "corrected_async_gn":
                lvar = m_fresh[1] - m_fresh[0] ** 2
                var = np.where(var < 0, lvar, var)
            var = var * ne / (ne - 1)
            xg = np.asarray(x2, np.float64)[:, i * h : (i + 1) * h].reshape(
                b, h, w, g, c // g
            )
            want = (xg - full[0][:, None, None, :, None]) / np.sqrt(
                var[:, None, None, :, None] + 1e-5
            )
            np.testing.assert_allclose(
                got[:, i * h : (i + 1) * h],
                want.reshape(b, h, w, c),
                atol=1e-4,
            )
        # refreshed state must hold x2's gathered moments
        want_state = np.stack([moments(np.asarray(x2)[:, j * h : (j + 1) * h]) for j in range(n)])
        np.testing.assert_allclose(np.asarray(state2["gn"]), want_state, atol=1e-5)


def test_patch_attention_sync_matches_dense(devices8):
    n, b, l, c, heads = 4, 2, 6, 8, 2
    mesh = sp_mesh(devices8, n)
    x = jax.random.normal(jax.random.PRNGKey(13), (b, l * n, c))
    keys = jax.random.split(jax.random.PRNGKey(14), 4)
    p = {
        "to_q": {"kernel": jax.random.normal(keys[0], (c, c)) * 0.3},
        "to_kv": {"kernel": jax.random.normal(keys[1], (c, 2 * c)) * 0.3},
        "to_out": {
            "kernel": jax.random.normal(keys[2], (c, c)) * 0.3,
            "bias": jax.random.normal(keys[3], (c,)) * 0.1,
        },
    }
    dense = attention(p, x, heads=heads)

    def wrapped(xl):
        ctx = PatchContext(n=n, mode="full_sync", phase=PHASE_SYNC)
        return patch_self_attention(p, xl, ctx, "attn", heads=heads)

    y = jax.jit(
        shard_map(wrapped, mesh=mesh, in_specs=P(None, SP_AXIS), out_specs=P(None, SP_AXIS))
    )(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), atol=1e-4)


def test_patch_attention_stale_kv(devices8):
    """Steady state: KV = fresh local slot + stale peer slots (attn.py:135-138)."""
    n, b, l, c, heads = 2, 1, 4, 4, 1
    mesh = sp_mesh(devices8, n)
    x1 = jax.random.normal(jax.random.PRNGKey(15), (b, l * n, c))
    x2 = jax.random.normal(jax.random.PRNGKey(16), (b, l * n, c))
    keys = jax.random.split(jax.random.PRNGKey(17), 3)
    p = {
        "to_q": {"kernel": jax.random.normal(keys[0], (c, c)) * 0.4},
        "to_kv": {"kernel": jax.random.normal(keys[1], (c, 2 * c)) * 0.4},
        "to_out": {"kernel": jax.random.normal(keys[2], (c, c)) * 0.4},
    }

    def fn(xl, ctx):
        return patch_self_attention(p, xl, ctx, "attn", heads=heads)

    def run(x, state, phase):
        def wrapped(xl, st):
            ctx = PatchContext(n=n, mode="corrected_async_gn", phase=phase, state_in=st)
            y = fn(xl, ctx)
            return y, ctx.state_out

        return jax.jit(
            shard_map(
                wrapped,
                mesh=mesh,
                in_specs=(P(None, SP_AXIS), None if state is None else jax.tree.map(lambda _: P(), state)),
                out_specs=(P(None, SP_AXIS), jax.tree.map(lambda _: P(), state) if state is not None else P()),
                check_vma=False,
            )
        )(x, state)

    _, state = run(x1, None, PHASE_SYNC)
    y2, state2 = run(x2, state, PHASE_STALE)

    # oracle: per patch i, kv rows of x2 for patch i, x1 for others
    from distrifuser_tpu.ops.linear import linear as jlin
    from distrifuser_tpu.ops.attention import sdpa as jsdpa, split_kv

    kv1 = np.asarray(jlin(p["to_kv"], x1))
    kv2 = np.asarray(jlin(p["to_kv"], x2))
    q2 = jlin(p["to_q"], x2)
    got = np.asarray(y2)
    for i in range(n):
        kv_mix = kv1.copy()
        kv_mix[:, i * l : (i + 1) * l] = kv2[:, i * l : (i + 1) * l]
        k, v = split_kv(jnp.asarray(kv_mix))
        out = jsdpa(q2[:, i * l : (i + 1) * l], k, v, heads=heads)
        want = np.asarray(jlin(p["to_out"], out))
        np.testing.assert_allclose(got[:, i * l : (i + 1) * l], want, atol=1e-4)
    # refreshed state holds x2's gathered kv
    want_state = np.stack([kv2[:, j * l : (j + 1) * l] for j in range(n)])
    np.testing.assert_allclose(np.asarray(state2["attn"]), want_state, atol=1e-5)


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

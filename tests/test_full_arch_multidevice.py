"""Full-architecture multi-device numerics (VERDICT r3 weak #4).

The tiny-config tests prove the mesh/collective wiring and the AOT leg
proves the real geometry compiles 8-way; this adds the missing piece —
the REAL `sdxl_config()` UNet executing a complete multi-device generation
and matching the single-device run.  It costs ~8-12 minutes of CPU compile
(two full-UNet program sets through one core), so it is gated behind
``DISTRIFUSER_TPU_HEAVY_TESTS=1`` rather than running in every suite pass.
Measured 2026-07-30: 2-dev cfg_split vs 1-dev max|diff| = 6.5e-05 (fp32,
256px, 2 steps) — recorded in BENCH_NOTES.md.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("DISTRIFUSER_TPU_HEAVY_TESTS") != "1",
    reason="~10 min of CPU compile; set DISTRIFUSER_TPU_HEAVY_TESTS=1",
)


def test_real_sdxl_two_device_matches_single(devices8):
    from distrifuser_tpu import DistriConfig
    from distrifuser_tpu.models import unet as unet_mod
    from distrifuser_tpu.parallel.runner import make_runner
    from distrifuser_tpu.schedulers import get_scheduler

    os.environ.setdefault("DISTRIFUSER_TPU_FLASH", "0")
    ucfg = unet_mod.sdxl_config()
    params = unet_mod.init_unet_params(jax.random.PRNGKey(0), ucfg, jnp.float32)
    lat = jax.random.normal(jax.random.PRNGKey(1), (1, 32, 32, ucfg.in_channels),
                            jnp.float32)
    enc = jax.random.normal(jax.random.PRNGKey(2),
                            (2, 1, 77, ucfg.cross_attention_dim), jnp.float32)
    ed = (ucfg.projection_class_embeddings_input_dim
          - 6 * ucfg.addition_time_embed_dim)
    added = {"text_embeds": jnp.zeros((2, 1, ed), jnp.float32),
             "time_ids": jnp.tile(jnp.asarray(
                 [256, 256, 0, 0, 256, 256], jnp.float32)[None, None],
                 (2, 1, 1))}

    outs = {}
    for n in (2, 1):
        cfg = DistriConfig(devices=devices8[:n], height=256, width=256,
                           warmup_steps=1, parallelism="patch")
        r = make_runner(cfg, ucfg, params, get_scheduler("ddim"))
        o = r.generate(lat, enc, guidance_scale=5.0, num_inference_steps=2,
                       added_cond=added)
        outs[n] = np.asarray(o)
        assert np.isfinite(outs[n]).all()
    assert np.abs(outs[2] - outs[1]).max() < 5e-4

"""bench.py's one-parseable-line contract.

Rounds 1-2 lost their benchmark gate to rc=124 with nothing parseable on
stdout; bench.py now guarantees exactly one JSON result line within its
total wall-clock budget (a real latency, or an explicit failure metric)
and a meaningful exit code.  These run the real script as the driver does.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(args, timeout):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    env.pop("XLA_FLAGS", None)  # single CPU device is fine and faster
    return subprocess.run(
        [sys.executable, BENCH, *args], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=REPO,
    )


def _parse_result(stdout):
    lines = [l for l in stdout.strip().splitlines() if l.startswith("{")]
    assert len(lines) == 1, f"expected exactly one JSON line, got: {stdout!r}"
    rec = json.loads(lines[0])
    assert set(rec) == {"metric", "value", "unit", "vs_baseline"}
    return rec


def test_normal_run_emits_real_latency():
    r = _run(["--steps", "2", "--test_times", "1"], timeout=420)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = _parse_result(r.stdout)
    assert rec["value"] > 0 and rec["unit"] == "s"
    assert "provenance" in r.stderr  # platform/dtype always logged


def test_expired_budget_still_emits_parseable_line():
    """Budget already spent at start: the watchdog must print the explicit
    timeout metric (never silence) and exit 2."""
    r = _run(["--steps", "2", "--test_times", "1", "--total_budget_s", "91"],
             timeout=300)
    assert r.returncode == 2, (r.returncode, r.stderr[-500:])
    rec = _parse_result(r.stdout)
    assert rec["metric"] == "bench_watchdog_timeout"
    assert rec["value"] == -1.0

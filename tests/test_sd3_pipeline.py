"""DistriSD3Pipeline: tiny random-weight MMDiT stack on the fake mesh."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distrifuser_tpu import DistriConfig, DistriSD3Pipeline
from distrifuser_tpu.models import mmdit as mm
from distrifuser_tpu.models.clip import (
    CLIPTextConfig,
    init_clip_params,
    tiny_clip_config,
)
from distrifuser_tpu.models.vae import init_vae_params, tiny_vae_config


def build_sd3_pipeline(devices, n_dev, **cfg_kw):
    cfg_kw.setdefault("height", 256)
    cfg_kw.setdefault("width", 256)
    cfg_kw.setdefault("warmup_steps", 1)
    dcfg = DistriConfig(devices=devices[:n_dev], **cfg_kw)
    # SD3-shaped tiny stack: CLIP hiddens concat to joint_attention_dim
    # (16+16=32); pooled widths concat to pooled_projection_dim (16+8=24)
    tc1 = tiny_clip_config(hidden=16)
    tc2 = CLIPTextConfig(
        vocab_size=1000, hidden_size=16, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=32, projection_dim=8,
    )
    mcfg = mm.tiny_mmdit_config()
    vcfg = tiny_vae_config()
    pipe = DistriSD3Pipeline.from_params(
        dcfg,
        mcfg,
        mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg),
        vcfg,
        init_vae_params(jax.random.PRNGKey(1), vcfg),
        [tc1, tc2],
        [init_clip_params(jax.random.PRNGKey(2), tc1),
         init_clip_params(jax.random.PRNGKey(3), tc2)],
    )
    return pipe, dcfg


def test_sd3_pipeline_generates_pil(devices8):
    pipe, _ = build_sd3_pipeline(devices8, 4)
    out = pipe("a red fox in the snow", num_inference_steps=3, seed=7)
    img = out.images[0]
    # tiny VAE has 2 blocks -> one 2x upsample: 32x32 latent -> 64x64 px
    assert img.size == (64, 64)
    assert out.weightless_tokenizer  # hash tokenizers -> artifact flagged


def test_sd3_deterministic_and_latent(devices8):
    pipe, dcfg = build_sd3_pipeline(devices8, 2)
    kw = dict(num_inference_steps=2, seed=4, output_type="latent")
    a = pipe("a corgi", **kw).images[0]
    b = pipe("a corgi", **kw).images[0]
    c = pipe("a corgi", num_inference_steps=2, seed=5,
             output_type="latent").images[0]
    np.testing.assert_array_equal(a, b)
    assert np.abs(a - c).max() > 0
    assert a.shape == (dcfg.latent_height, dcfg.latent_width, 4)
    assert np.isfinite(a).all()


def test_sd3_multi_device_matches_single(devices8):
    """Pipeline-level golden test: full_sync multi-device equals the
    single-device run above the reference's 30 dB quality bar."""
    pipe1, _ = build_sd3_pipeline(devices8, 1)
    pipe4, _ = build_sd3_pipeline(devices8, 4, mode="full_sync")
    kw = dict(num_inference_steps=3, seed=11, output_type="np")
    img1 = pipe1("a lighthouse at dusk", **kw).images[0]
    img4 = pipe4("a lighthouse at dusk", **kw).images[0]
    mse = float(np.mean((img1 - img4) ** 2))
    psnr = 10 * np.log10(1.0 / max(mse, 1e-12))
    assert psnr > 30, f"PSNR {psnr:.1f} dB"


def test_sd3_batch_and_num_images(devices8):
    pipe, _ = build_sd3_pipeline(devices8, 2, batch_size=2)
    out = pipe(["a cat", "a dog", "a bird"], num_inference_steps=2,
               output_type="latent")
    assert len(out.images) == 3
    two = pipe("a cat", num_images_per_prompt=2, num_inference_steps=2,
               output_type="latent")
    assert len(two.images) == 2
    assert np.abs(two.images[0] - two.images[1]).max() > 0


def test_sd3_pooled_width_validation(devices8):
    tc1 = tiny_clip_config(hidden=16)
    tc2 = tiny_clip_config(hidden=16)  # pooled sums to 32 != 24
    mcfg = mm.tiny_mmdit_config()
    vcfg = tiny_vae_config()
    with pytest.raises(ValueError, match="pooled_projection_dim"):
        DistriSD3Pipeline.from_params(
            DistriConfig(devices=devices8[:1], height=256, width=256),
            mcfg, mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg),
            vcfg, init_vae_params(jax.random.PRNGKey(1), vcfg),
            [tc1, tc2],
            [init_clip_params(jax.random.PRNGKey(2), tc1),
             init_clip_params(jax.random.PRNGKey(3), tc2)],
        )


def test_scheduler_family_guards(devices8):
    """Scheduler/model-family crosses fail at construction (code-review r5):
    a diffusion sampler on the flow MMDiT and flow-euler on the epsilon
    UNet both produce silent garbage if allowed through."""
    tc1 = tiny_clip_config(hidden=16)
    tc2 = CLIPTextConfig(
        vocab_size=1000, hidden_size=16, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=32, projection_dim=8,
    )
    mcfg = mm.tiny_mmdit_config()
    vcfg = tiny_vae_config()
    args = (
        DistriConfig(devices=devices8[:1], height=256, width=256),
        mcfg, mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg),
        vcfg, init_vae_params(jax.random.PRNGKey(1), vcfg),
        [tc1, tc2],
        [init_clip_params(jax.random.PRNGKey(2), tc1),
         init_clip_params(jax.random.PRNGKey(3), tc2)],
    )
    with pytest.raises(ValueError, match="rectified-flow"):
        DistriSD3Pipeline.from_params(*args, scheduler="ddim")
    # and the reverse cross on the UNet family
    from distrifuser_tpu.models.clip import init_clip_params as icp
    from distrifuser_tpu.models.clip import tiny_clip_config as tcc
    from distrifuser_tpu.models.unet import init_unet_params, tiny_config
    from distrifuser_tpu.pipelines import DistriSDPipeline

    tc = tcc(hidden=32)
    ucfg = tiny_config(cross_attention_dim=32, sdxl=False)
    with pytest.raises(ValueError, match="flow-euler"):
        DistriSDPipeline.from_params(
            DistriConfig(devices=devices8[:1], height=128, width=128),
            ucfg, init_unet_params(jax.random.PRNGKey(0), ucfg),
            vcfg, init_vae_params(jax.random.PRNGKey(1), vcfg),
            [tc], [icp(jax.random.PRNGKey(2), tc)],
            scheduler="flow-euler",
        )


def test_sd3_img2img_strength(devices8):
    """img2img under rectified flow: low strength stays near the init
    latent, full strength ignores it (the SD-pipeline contract on the
    flow interpolant)."""
    from distrifuser_tpu.models import vae as vae_mod

    pipe, dcfg = build_sd3_pipeline(devices8, 1)
    rng = np.random.RandomState(8)
    im = rng.rand(64, 64, 3).astype(np.float32)
    init = np.asarray((vae_mod.encode(
        pipe.vae_params, pipe.vae_config, jnp.asarray((im * 2 - 1)[None])
    ) - pipe.vae_config.shift_factor) * pipe.vae_config.scaling_factor)
    kw = dict(num_inference_steps=8, output_type="latent", seed=3)
    d = {}
    for s in (0.25, 1.0):
        out = pipe("a cabin", image=im, strength=s, **kw).images[0]
        d[s] = float(np.abs(out - init[0]).mean())
    assert d[0.25] < d[1.0], d


def test_sd3_pipeline_callback(devices8):
    """Pipeline-level callback (default compiled mode): fires per step with
    padded tail rows stripped."""
    pipe, dcfg = build_sd3_pipeline(devices8, 2)
    seen = []
    out = pipe("a fox", num_inference_steps=3, output_type="latent", seed=1,
               callback=lambda i, t, x: seen.append((i, float(t), x.shape)))
    assert [i for i, _, _ in seen] == [0, 1, 2]
    ts = [t for _, t, _ in seen]
    assert ts == sorted(ts, reverse=True)
    assert all(s == (1, dcfg.latent_height, dcfg.latent_width, 4)
               for _, _, s in seen)
    assert np.isfinite(out.images[0]).all()


def test_sd3_from_pretrained_synthetic_snapshot(tmp_path, devices8):
    """from_pretrained over a synthetic diffusers-layout SD3 snapshot:
    config discovery (transformer/vae/two projection CLIPs), sharded
    safetensors loading, conversion, the scheduler_config flow shift, the
    optional-T5-absent path, and generation all engage — only the weight
    values are synthetic."""
    import json

    from safetensors.numpy import save_file

    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from test_mmdit_weights import CFG as MCFG
    from test_mmdit_weights import synth_sd
    from test_weights_roundtrip import invert_tree

    root = tmp_path / "snap"
    for sub in ("transformer", "vae", "text_encoder", "text_encoder_2",
                "scheduler"):
        (root / sub).mkdir(parents=True)

    with open(root / "transformer" / "config.json", "w") as f:
        json.dump({
            "sample_size": MCFG.sample_size, "patch_size": MCFG.patch_size,
            "in_channels": MCFG.in_channels, "num_layers": MCFG.depth,
            "num_attention_heads": MCFG.num_heads,
            "attention_head_dim": MCFG.hidden_size // MCFG.num_heads,
            "joint_attention_dim": MCFG.joint_attention_dim,
            "pooled_projection_dim": MCFG.pooled_projection_dim,
            "pos_embed_max_size": MCFG.pos_embed_max_size,
        }, f)
    save_file(synth_sd(),
              str(root / "transformer" / "diffusion_pytorch_model.safetensors"))

    import transformers
    import torch

    for sub, proj in (("text_encoder", 16), ("text_encoder_2", 8)):
        hf_cfg = transformers.CLIPTextConfig(
            vocab_size=1000, hidden_size=16, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=32,
            max_position_embeddings=77, projection_dim=proj,
            eos_token_id=999, bos_token_id=998,
        )
        torch.manual_seed(proj)
        model = transformers.CLIPTextModelWithProjection(hf_cfg).eval()
        save_file({k: v.numpy() for k, v in model.state_dict().items()},
                  str(root / sub / "model.safetensors"))
        with open(root / sub / "config.json", "w") as f:
            json.dump({
                "architectures": ["CLIPTextModelWithProjection"],
                "vocab_size": 1000, "hidden_size": 16,
                "num_hidden_layers": 2, "num_attention_heads": 4,
                "intermediate_size": 32, "max_position_embeddings": 77,
                "projection_dim": proj, "eos_token_id": 999,
            }, f)

    vcfg = tiny_vae_config()
    vparams = init_vae_params(jax.random.PRNGKey(1), vcfg)
    vsd = {}
    invert_tree(jax.tree.map(np.asarray, vparams), "", vsd)
    save_file(vsd, str(root / "vae" / "diffusion_pytorch_model.safetensors"))
    with open(root / "vae" / "config.json", "w") as f:
        json.dump({"block_out_channels": [16, 32], "layers_per_block": 1,
                   "norm_num_groups": 8, "scaling_factor": 1.2,
                   "shift_factor": 0.1}, f)

    with open(root / "scheduler" / "scheduler_config.json", "w") as f:
        json.dump({"_class_name": "FlowMatchEulerDiscreteScheduler",
                   "shift": 2.0, "num_train_timesteps": 1000}, f)

    cfg = DistriConfig(devices=devices8[:4], height=256, width=256,
                       warmup_steps=1, dtype=jnp.float32)
    pipe = DistriSD3Pipeline.from_pretrained(cfg, str(root))
    assert pipe.scheduler.shift == 2.0          # flow shift plumbed
    assert pipe.vae_config.shift_factor == 0.1  # latent re-centering
    assert pipe.mmdit_config.depth == MCFG.depth
    assert pipe.t5 == (None, None)              # optional T5 absent
    out = pipe(prompt="snapshot smoke", num_inference_steps=2,
               output_type="np")
    assert np.asarray(out.images[0]).shape == (64, 64, 3)
    assert np.isfinite(np.asarray(out.images[0])).all()
    # explicit diffusion-scheduler strings are rejected, not ignored
    with pytest.raises(ValueError, match="flow-euler"):
        DistriSD3Pipeline.from_pretrained(cfg, str(root), scheduler="ddim")


def test_sd3_with_t5_encoder(devices8):
    """The triple-encoder path with a real (tiny) T5: its states append
    along the token axis after the zero-padded CLIP block, and the run
    differs from the zeros-for-T5 degraded path."""
    from distrifuser_tpu.models import t5 as t5_mod

    tc1 = tiny_clip_config(hidden=16)
    tc2 = CLIPTextConfig(
        vocab_size=1000, hidden_size=16, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=32, projection_dim=8,
    )
    mcfg = mm.tiny_mmdit_config()
    t5cfg = t5_mod.tiny_t5_config()
    assert t5cfg.d_model == mcfg.joint_attention_dim
    vcfg = tiny_vae_config()
    common = dict(
        distri_config=DistriConfig(devices=devices8[:2], height=256,
                                   width=256, warmup_steps=1),
        mmdit_config=mcfg,
        mmdit_params=mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg),
        vae_config=vcfg,
        vae_params=init_vae_params(jax.random.PRNGKey(1), vcfg),
        clip_configs=[tc1, tc2],
        clip_params=[init_clip_params(jax.random.PRNGKey(2), tc1),
                     init_clip_params(jax.random.PRNGKey(3), tc2)],
        max_t5_tokens=7,
    )
    with_t5 = DistriSD3Pipeline.from_params(
        t5_config=t5cfg,
        t5_params=t5_mod.init_t5_params(jax.random.PRNGKey(4), t5cfg),
        **common,
    )
    without = DistriSD3Pipeline.from_params(**common)
    enc, pooled = with_t5._encode(["a fox"], [""])
    assert enc.shape == (2, 1, 77 + 7, mcfg.joint_attention_dim)
    assert pooled.shape == (2, 1, mcfg.pooled_projection_dim)
    # T5 block is non-zero here, zero in the degraded path
    assert np.abs(np.asarray(enc[:, :, 77:])).max() > 0
    enc0, _ = without._encode(["a fox"], [""])
    np.testing.assert_array_equal(np.asarray(enc0[:, :, 77:]), 0.0)
    kw = dict(num_inference_steps=2, output_type="latent", seed=5)
    a = with_t5("a fox", **kw).images[0]
    b = without("a fox", **kw).images[0]
    assert np.isfinite(a).all()
    assert np.abs(a - b).max() > 0


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

"""Hand-assembled torch reference modules with diffusers' composition and
state_dict naming — the ground truth for converter/architecture parity tests
(diffusers itself is not installed in this image; these are plain torch.nn
recreations of its module graph, built from the published architecture).

Used by tests/test_torch_parity_blocks.py (block level) and
tests/test_torch_parity_unet.py (the full UNet2DConditionModel graph incl.
skip-connection routing, down/upsampling placement, and time/added
embeddings).
"""

import math

import torch
import torch.nn.functional as F


class TorchAttn(torch.nn.Module):
    """diffusers Attention core: q/k/v proj, SDPA, out proj (residual lives
    in the caller, residual_connection=False there)."""

    def __init__(self, c, heads, c_enc=None, d=None):
        super().__init__()
        d = d or c // heads
        inner = heads * d
        self.heads, self.d = heads, d
        self.to_q = torch.nn.Linear(c, inner, bias=False)
        self.to_k = torch.nn.Linear(c_enc or c, inner, bias=False)
        self.to_v = torch.nn.Linear(c_enc or c, inner, bias=False)
        self.to_out = torch.nn.ModuleList([torch.nn.Linear(inner, c)])

    def forward(self, x, enc=None):
        enc = x if enc is None else enc
        b, l, _ = x.shape

        def split(t):
            return t.view(b, -1, self.heads, self.d).transpose(1, 2)

        y = F.scaled_dot_product_attention(
            split(self.to_q(x)), split(self.to_k(enc)), split(self.to_v(enc))
        )
        return self.to_out[0](y.transpose(1, 2).reshape(b, l, -1))


class TorchGEGLUFF(torch.nn.Module):
    """diffusers FeedForward with GEGLU: net.0.proj -> chunk -> a*gelu(g) -> net.2."""

    def __init__(self, c, mult=4):
        super().__init__()
        inner = c * mult
        proj = torch.nn.Linear(c, inner * 2)
        self.net = torch.nn.ModuleList(
            [torch.nn.Module(), torch.nn.Identity(), torch.nn.Linear(inner, c)]
        )
        self.net[0].proj = proj

    def forward(self, x):
        a, g = self.net[0].proj(x).chunk(2, dim=-1)
        return self.net[2](a * F.gelu(g))


class TorchBasicTransformerBlock(torch.nn.Module):
    """LN -> self-attn -> +res; LN -> cross-attn -> +res; LN -> FF -> +res."""

    def __init__(self, c, heads, c_enc):
        super().__init__()
        self.norm1 = torch.nn.LayerNorm(c)
        self.attn1 = TorchAttn(c, heads)
        self.norm2 = torch.nn.LayerNorm(c)
        self.attn2 = TorchAttn(c, heads, c_enc=c_enc)
        self.norm3 = torch.nn.LayerNorm(c)
        self.ff = TorchGEGLUFF(c)

    def forward(self, x, enc):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), enc)
        x = x + self.ff(self.norm3(x))
        return x


class TorchTransformer2D(torch.nn.Module):
    """Transformer2DModel wrapper: GN(eps=1e-6) -> proj_in (linear or 1x1
    conv; flatten order differs between the modes) -> blocks -> proj_out ->
    +residual."""

    def __init__(self, c, heads, c_enc, groups, use_linear, n_layers=1):
        super().__init__()
        self.use_linear = use_linear
        self.norm = torch.nn.GroupNorm(groups, c, eps=1e-6)
        if use_linear:
            self.proj_in = torch.nn.Linear(c, c)
            self.proj_out = torch.nn.Linear(c, c)
        else:
            self.proj_in = torch.nn.Conv2d(c, c, 1)
            self.proj_out = torch.nn.Conv2d(c, c, 1)
        self.transformer_blocks = torch.nn.ModuleList(
            [TorchBasicTransformerBlock(c, heads, c_enc) for _ in range(n_layers)]
        )

    def forward(self, x, enc):
        b, c, h, w = x.shape
        res = x
        hs = self.norm(x)
        if self.use_linear:
            hs = hs.permute(0, 2, 3, 1).reshape(b, h * w, c)
            hs = self.proj_in(hs)
        else:
            hs = self.proj_in(hs)
            hs = hs.permute(0, 2, 3, 1).reshape(b, h * w, c)
        for blk in self.transformer_blocks:
            hs = blk(hs, enc)
        if self.use_linear:
            hs = self.proj_out(hs)
            hs = hs.reshape(b, h, w, c).permute(0, 3, 1, 2)
        else:
            hs = hs.reshape(b, h, w, c).permute(0, 3, 1, 2)
            hs = self.proj_out(hs)
        return hs + res


class TorchResnetBlock2D(torch.nn.Module):
    """GN -> silu -> conv -> +time proj -> GN -> silu -> conv -> +shortcut."""

    def __init__(self, cin, cout, temb_dim, groups):
        super().__init__()
        self.norm1 = torch.nn.GroupNorm(groups, cin)
        self.conv1 = torch.nn.Conv2d(cin, cout, 3, padding=1)
        self.time_emb_proj = torch.nn.Linear(temb_dim, cout)
        self.norm2 = torch.nn.GroupNorm(groups, cout)
        self.conv2 = torch.nn.Conv2d(cout, cout, 3, padding=1)
        if cin != cout:
            self.conv_shortcut = torch.nn.Conv2d(cin, cout, 1)

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + self.time_emb_proj(F.silu(temb))[:, :, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        if hasattr(self, "conv_shortcut"):
            x = self.conv_shortcut(x)
        return x + h


def torch_timestep_embedding(t, dim, flip_sin_to_cos=True, freq_shift=0,
                             max_period=10000):
    """diffusers get_timestep_embedding, transcribed in torch."""
    half = dim // 2
    exponent = -math.log(max_period) * torch.arange(half, dtype=torch.float32)
    exponent = exponent / (half - freq_shift)
    emb = t.float()[:, None] * torch.exp(exponent)[None, :]
    emb = torch.cat([torch.sin(emb), torch.cos(emb)], dim=-1)
    if flip_sin_to_cos:
        emb = torch.cat([emb[:, half:], emb[:, :half]], dim=-1)
    return emb


class TorchTimestepEmbedding(torch.nn.Module):
    def __init__(self, cin, temb_dim):
        super().__init__()
        self.linear_1 = torch.nn.Linear(cin, temb_dim)
        self.linear_2 = torch.nn.Linear(temb_dim, temb_dim)

    def forward(self, x):
        return self.linear_2(F.silu(self.linear_1(x)))


class TorchUNet(torch.nn.Module):
    """The full UNet2DConditionModel graph for a distrifuser_tpu UNetConfig,
    with diffusers state_dict naming throughout so convert_unet_state_dict
    digests self.state_dict() directly."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        ch0 = cfg.block_out_channels[0]
        temb_dim = cfg.time_embed_dim
        groups = cfg.norm_num_groups
        cross = cfg.cross_attention_dim

        self.conv_in = torch.nn.Conv2d(cfg.in_channels, ch0, 3, padding=1)
        self.time_embedding = TorchTimestepEmbedding(ch0, temb_dim)
        if cfg.addition_embed_type == "text_time":
            self.add_embedding = TorchTimestepEmbedding(
                cfg.projection_class_embeddings_input_dim, temb_dim
            )

        def transformer(c, heads, n_layers):
            return TorchTransformer2D(
                c, heads, cross, groups, cfg.use_linear_projection, n_layers
            )

        self.down_blocks = torch.nn.ModuleList()
        out_ch = ch0
        for i, btype in enumerate(cfg.down_block_types):
            in_ch, out_ch = out_ch, cfg.block_out_channels[i]
            block = torch.nn.Module()
            block.resnets = torch.nn.ModuleList(
                [
                    TorchResnetBlock2D(
                        in_ch if j == 0 else out_ch, out_ch, temb_dim, groups
                    )
                    for j in range(cfg.layers_per_block)
                ]
            )
            if btype == "CrossAttnDownBlock2D":
                block.attentions = torch.nn.ModuleList(
                    [
                        transformer(out_ch, cfg.heads_for_block(i),
                                    cfg.transformer_layers_per_block[i])
                        for _ in range(cfg.layers_per_block)
                    ]
                )
            if i < len(cfg.down_block_types) - 1:
                ds = torch.nn.Module()
                ds.conv = torch.nn.Conv2d(out_ch, out_ch, 3, stride=2, padding=1)
                block.downsamplers = torch.nn.ModuleList([ds])
            self.down_blocks.append(block)

        mid_ch = cfg.block_out_channels[-1]
        self.mid_block = torch.nn.Module()
        self.mid_block.resnets = torch.nn.ModuleList(
            [
                TorchResnetBlock2D(mid_ch, mid_ch, temb_dim, groups),
                TorchResnetBlock2D(mid_ch, mid_ch, temb_dim, groups),
            ]
        )
        self.mid_block.attentions = torch.nn.ModuleList(
            [
                transformer(
                    mid_ch,
                    cfg.heads_for_block(len(cfg.block_out_channels) - 1),
                    cfg.transformer_layers_per_block[-1],
                )
            ]
        )

        self.up_blocks = torch.nn.ModuleList()
        rev = list(reversed(cfg.block_out_channels))
        rev_tf = list(reversed(cfg.transformer_layers_per_block))
        prev_out = rev[0]
        for i, btype in enumerate(cfg.up_block_types):
            out_ch = rev[i]
            in_ch = rev[min(i + 1, len(rev) - 1)]
            block = torch.nn.Module()
            resnets = []
            for j in range(cfg.layers_per_block + 1):
                skip_ch = in_ch if j == cfg.layers_per_block else out_ch
                res_in = prev_out if j == 0 else out_ch
                resnets.append(
                    TorchResnetBlock2D(res_in + skip_ch, out_ch, temb_dim, groups)
                )
            block.resnets = torch.nn.ModuleList(resnets)
            if btype == "CrossAttnUpBlock2D":
                block.attentions = torch.nn.ModuleList(
                    [
                        transformer(out_ch, cfg.heads_for_block(len(rev) - 1 - i),
                                    rev_tf[i])
                        for _ in range(cfg.layers_per_block + 1)
                    ]
                )
            if i < len(cfg.up_block_types) - 1:
                us = torch.nn.Module()
                us.conv = torch.nn.Conv2d(out_ch, out_ch, 3, padding=1)
                block.upsamplers = torch.nn.ModuleList([us])
            prev_out = out_ch
            self.up_blocks.append(block)

        self.conv_norm_out = torch.nn.GroupNorm(groups, ch0)
        self.conv_out = torch.nn.Conv2d(ch0, cfg.out_channels, 3, padding=1)

    def forward(self, sample, timesteps, enc, added_cond=None):
        cfg = self.cfg
        temb = torch_timestep_embedding(
            timesteps, cfg.block_out_channels[0],
            flip_sin_to_cos=cfg.flip_sin_to_cos, freq_shift=cfg.freq_shift,
        )
        temb = self.time_embedding(temb)
        if cfg.addition_embed_type == "text_time":
            b = sample.shape[0]
            tid = torch_timestep_embedding(
                added_cond["time_ids"].reshape(-1), cfg.addition_time_embed_dim,
                flip_sin_to_cos=cfg.flip_sin_to_cos, freq_shift=cfg.freq_shift,
            ).reshape(b, -1)
            temb = temb + self.add_embedding(
                torch.cat([added_cond["text_embeds"], tid], dim=-1)
            )

        x = self.conv_in(sample)
        skips = [x]
        for i, btype in enumerate(cfg.down_block_types):
            block = self.down_blocks[i]
            for j in range(cfg.layers_per_block):
                x = block.resnets[j](x, temb)
                if btype == "CrossAttnDownBlock2D":
                    x = block.attentions[j](x, enc)
                skips.append(x)
            if i < len(cfg.down_block_types) - 1:
                x = block.downsamplers[0].conv(x)
                skips.append(x)

        x = self.mid_block.resnets[0](x, temb)
        x = self.mid_block.attentions[0](x, enc)
        x = self.mid_block.resnets[1](x, temb)

        for i, btype in enumerate(cfg.up_block_types):
            block = self.up_blocks[i]
            for j in range(cfg.layers_per_block + 1):
                x = torch.cat([x, skips.pop()], dim=1)
                x = block.resnets[j](x, temb)
                if btype == "CrossAttnUpBlock2D":
                    x = block.attentions[j](x, enc)
            if i < len(cfg.up_block_types) - 1:
                x = F.interpolate(x, scale_factor=2, mode="nearest")
                x = block.upsamplers[0].conv(x)

        assert not skips
        x = F.silu(self.conv_norm_out(x))
        return self.conv_out(x)

"""Full-architecture weight-converter roundtrip.

Builds a diffusers-style torch state_dict by *inverting* our param tree for
the full tiny UNet (covering every layer family: resnets, transformers,
samplers, time/add embeddings), then requires convert_unet_state_dict to
reproduce the original tree exactly.  This pins the layout rules (HWIO
transpose, linear transpose, norm scale naming, to_k/to_v fusion,
ff.net renames) against the whole architecture rather than hand-picked keys
— the silent-transposition failure mode SURVEY.md §7 ranks among the hard
parts.
"""

import jax
import numpy as np
import pytest

from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.models.weights import (
    convert_unet_state_dict,
    load_params,
    params_nbytes,
    quantize_params,
    save_params,
)


def _emit(sd, prefix, leaf_name, arr):
    sd[f"{prefix}.{leaf_name}" if prefix else leaf_name] = np.asarray(arr)


def invert_tree(tree, prefix, sd):
    """Our param tree -> torch-style state_dict names/layouts."""
    if isinstance(tree, list):
        for i, v in enumerate(tree):
            invert_tree(v, f"{prefix}.{i}", sd)
        return
    assert isinstance(tree, dict)
    keys = set(tree)
    if keys == {"kernel"} or keys == {"kernel", "bias"}:
        k = np.asarray(tree["kernel"])
        if k.ndim == 4:
            _emit(sd, prefix, "weight", k.transpose(3, 2, 0, 1))
        else:
            _emit(sd, prefix, "weight", k.T)
        if "bias" in tree:
            _emit(sd, prefix, "bias", tree["bias"])
        return
    if keys == {"scale", "bias"}:
        _emit(sd, prefix, "weight", tree["scale"])
        _emit(sd, prefix, "bias", tree["bias"])
        return
    for name, sub in tree.items():
        path = f"{prefix}.{name}" if prefix else name
        if name == "to_kv":
            kk = np.asarray(sub["kernel"])
            half = kk.shape[1] // 2
            base = prefix  # attention module path
            _emit(sd, base, "to_k.weight", kk[:, :half].T)
            _emit(sd, base, "to_v.weight", kk[:, half:].T)
            continue
        if name == "to_out":
            invert_tree(sub, f"{prefix}.to_out.0", sd)
            continue
        if name == "net_0":
            invert_tree(sub, f"{prefix}.net.0", sd)
            continue
        if name == "net_2":
            invert_tree(sub, f"{prefix}.net.2", sd)
            continue
        invert_tree(sub, path, sd)


def test_full_unet_converter_roundtrip():
    for sdxl in (False, True):
        cfg = tiny_config(sdxl=sdxl)
        params = init_unet_params(jax.random.PRNGKey(0), cfg)
        sd = {}
        invert_tree(params, "", sd)
        back = convert_unet_state_dict(sd)
        assert jax.tree.structure(params) == jax.tree.structure(back), (
            "converted tree structure diverges from the native one"
        )
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_converted_then_quantized_tree_npz_roundtrip(tmp_path, mode):
    """Conversion + quantization runs ONCE: a state_dict converted and
    quantized tree saved to the flat .npz (int8/fp8 payload + fp32 scales
    in the same archive) loads back bit-exactly — same structure, same
    payload/scale/compute dtypes, same closed-form byte count — so a
    server restart mmaps the cache instead of re-quantizing."""
    from distrifuser_tpu.parallel.compress import (QuantizedTensor,
                                                   fp8_supported)

    if mode == "fp8" and not fp8_supported():
        pytest.skip("no float8_e4m3fn in this jax build")
    cfg = tiny_config(sdxl=False)
    params = init_unet_params(jax.random.PRNGKey(0), cfg)
    sd = {}
    invert_tree(params, "", sd)
    converted = convert_unet_state_dict(sd)
    q = quantize_params(converted, mode)
    path = str(tmp_path / "quantized.npz")
    save_params(path, q)
    back = load_params(path)
    assert jax.tree.structure(q) == jax.tree.structure(back)
    kinds = set()
    for a, b in zip(jax.tree.leaves(q), jax.tree.leaves(back)):
        assert a.dtype == b.dtype
        kinds.add(str(a.dtype))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert params_nbytes(back) == params_nbytes(q)
    # the archive really held 1-byte payloads, not silently-densified trees
    assert isinstance(back["conv_in"]["kernel"], QuantizedTensor)
    payload = "int8" if mode == "int8" else "float8_e4m3fn"
    assert payload in kinds


@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_npz_roundtrip_partial_channel_tile_and_byte_view(tmp_path, mode):
    """Regression (ISSUE 12): archives must restore payload dtype AND
    tile-scale alignment.  With grouped channel tiles the scale length is
    ceil(out/tile) — NOT derivable from the payload shape when the last
    tile is partial — so a loader that dropped the tile size would
    rebuild per-channel-misaligned QuantizedTensors (the constructor now
    refuses that).  fp8 payloads additionally store as explicit uint8
    byte views (numpy's void round-trip of ml_dtypes is
    version-fragile); the recorded dtype views them back."""
    from distrifuser_tpu.parallel.compress import (QuantizedTensor,
                                                   fp8_supported,
                                                   quantize_weight)

    if mode == "fp8" and not fp8_supported():
        pytest.skip("no float8_e4m3fn in this jax build")
    import jax.numpy as jnp

    rng = np.random.RandomState(7)
    # 50 output channels at tile 16 -> 4 scale tiles, last one partial;
    # bf16 compute dtype exercises the byte-view dense-leaf path too
    tree = {
        "layer": {
            "kernel": quantize_weight(
                jnp.asarray(rng.randn(24, 50), jnp.bfloat16), mode,
                channel_tile=16),
            "bias": jnp.zeros((50,), jnp.bfloat16),
        }
    }
    path = str(tmp_path / f"ct_{mode}.npz")
    save_params(path, tree)
    # the archive holds no ml_dtypes-void payloads (uint8/int8 views only)
    raw = np.load(path)
    assert all(raw[k].dtype.kind != "V" for k in raw.files), {
        k: raw[k].dtype for k in raw.files}
    back = load_params(path)
    qt = back["layer"]["kernel"]
    assert isinstance(qt, QuantizedTensor)
    assert qt.channel_tile == 16 and qt.scale.shape == (4,)
    assert qt.payload.dtype == tree["layer"]["kernel"].payload.dtype
    assert qt.dtype == jnp.bfloat16
    for a, b in [(tree["layer"]["kernel"].payload, qt.payload),
                 (tree["layer"]["kernel"].scale, qt.scale)]:
        np.testing.assert_array_equal(
            np.asarray(a).view(np.uint8), np.asarray(b).view(np.uint8))
    # dequantized values identical -> the forward is bit-stable across a
    # server restart
    np.testing.assert_array_equal(
        np.asarray(tree["layer"]["kernel"].__jax_array__(), np.float32),
        np.asarray(qt.__jax_array__(), np.float32))
    assert params_nbytes(back) == params_nbytes(tree)

"""Quantized-weight serving (DistriConfig.weight_quant, ISSUE 6): per-tile
round-trip bounds, tree-level quantization policy, three-family end-to-end
parity at the pinned tolerances, "none" bit-identity, npz save/load
equivalence, ExecKey separation in one executor fleet, and the resilience
ladder's weight_quant_on rung under injected OOM."""

import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.models.weights import (
    dequantize_params,
    load_params,
    params_nbytes,
    quantize_params,
    save_params,
)
from distrifuser_tpu.parallel.compress import (
    QuantizedTensor,
    asdense,
    fp8_supported,
    quantize,
    dequantize,
    quantize_weight,
    validate_weight_mode,
)
from distrifuser_tpu.serve import (
    CircuitBreaker,
    DegradationLadder,
    ExecKey,
    InferenceServer,
    ResilienceConfig,
    ServeConfig,
)
from distrifuser_tpu.serve.faults import InjectedResourceExhausted
from distrifuser_tpu.serve.resilience import (
    RUNG_WEIGHT_QUANT,
    KeyResilience,
)
from distrifuser_tpu.serve.testing import FakeExecutor

from test_pipelines import build_sd_pipeline

# the pinned per-family parity tolerances (docs/PERF.md "Quantized
# weights"; scripts/bench_weights.py gates CI on the same numbers)
TOL = {"unet": 1e-2, "dit": 3e-3, "mmdit": 3e-3}

MODES = ["int8"] + (["fp8"] if fp8_supported() else [])


# --------------------------------------------------------------------------
# per-tile quantize/dequantize round-trip bounds
# --------------------------------------------------------------------------


@pytest.mark.parametrize("mode", MODES)
def test_weight_roundtrip_error_bounded_per_tile(mode):
    w = jax.random.normal(jax.random.PRNGKey(0), (6, 48, 32)) * jnp.exp(
        jax.random.normal(jax.random.PRNGKey(1), (6, 1, 32)) * 2
    )  # per-(block, out-channel) magnitude spread: per-tile scales must adapt
    qt = quantize_weight(w, mode)
    err = np.abs(np.asarray(asdense(qt), np.float64) - np.asarray(w, np.float64))
    # symmetric rounding: |err| <= scale/2 per int8 tile; fp8 e4m3 has a
    # 3-bit mantissa -> relative ~2^-4 of the tile amax
    amax = np.abs(np.asarray(w, np.float64)).max(axis=-2, keepdims=True)
    bound = amax / 254.0 if mode == "int8" else amax / 16.0
    assert (err <= bound + 1e-7).all()
    assert qt.shape == w.shape and qt.dtype == w.dtype
    # scale reduces the second-to-last (reduction) axis only
    assert qt.scale.shape == (6, 32)


def test_weight_quantize_zeros_and_nbytes():
    w = jnp.zeros((16, 8))
    qt = quantize_weight(w, "int8")
    assert (np.asarray(qt.payload) == 0).all()
    assert (np.asarray(asdense(qt)) == 0).all()
    # HBM residency: 1-byte payload + fp32 scale per output channel
    assert qt.nbytes == 16 * 8 + 8 * 4
    # asdense is the identity on plain arrays
    assert asdense(w) is w


def test_wire_quantize_axis_parameter_matches_wire_granularity():
    """axis=-1 (the PR-4 wire default) and axis=-2 (the weight tile) are
    the same machinery: round-tripping either way stays within the tile
    bound of its own axis."""
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 6, 8))
    for axis in (-1, -2):
        q, s = quantize(x, "int8", axis=axis)
        back = dequantize(q, s, x.dtype, axis=axis)
        amax = np.abs(np.asarray(x)).max(axis=axis, keepdims=True)
        assert (np.abs(np.asarray(back) - np.asarray(x))
                <= amax / 254.0 + 1e-7).all()


def test_validate_weight_mode():
    validate_weight_mode("none")
    validate_weight_mode("int8")
    with pytest.raises(ValueError, match="weight_quant"):
        validate_weight_mode("int8_residual")  # wire-only mode
    with pytest.raises(ValueError, match="weight_quant"):
        validate_weight_mode("int4")


# --------------------------------------------------------------------------
# tree-level policy (models/weights.quantize_params)
# --------------------------------------------------------------------------


def test_quantize_params_policy_and_bytes():
    params = init_unet_params(jax.random.PRNGKey(0), tiny_config())
    q = quantize_params(params, "int8")
    # structure-preserving: same dict/list skeleton
    assert jax.tree.structure(q) != jax.tree.structure(params)  # QT leaves
    # matmul/conv kernels quantize ...
    assert isinstance(q["conv_in"]["kernel"], QuantizedTensor)
    # ... but the OUTPUT HEAD stays dense (PTQ policy, docs/PERF.md) ...
    assert not isinstance(q["conv_out"]["kernel"], QuantizedTensor)
    # ... and norm scales / biases stay dense
    assert q["conv_in"]["bias"].dtype == params["conv_in"]["bias"].dtype
    assert not isinstance(q["conv_in"]["bias"], QuantizedTensor)
    # the knob exists for this number: >= 1.7x denoiser byte reduction
    assert params_nbytes(params) / params_nbytes(q) >= 1.7
    # "none" is the identity, not a copy
    assert quantize_params(params, "none") is params
    # idempotent at the same mode: a pre-quantized .npz cache loads
    # straight into a weight_quant="int8" pipeline (quantized leaves kept
    # by identity, nothing requantized)
    q2 = quantize_params(q, "int8")
    assert q2["conv_in"]["kernel"] is q["conv_in"]["kernel"]
    # a MODE SWITCH would requantize quantized values: refuse
    if fp8_supported():
        with pytest.raises(ValueError, match="already quantized"):
            quantize_params(q, "fp8")
    # "none" on an already-quantized tree would silently serve quantized
    # numerics under a full-precision identity (config / weight_report /
    # ExecKey all claiming "none"): refuse just as loudly
    with pytest.raises(ValueError, match="bit-identity"):
        quantize_params(q, "none")
    # dequantize_params densifies every QT leaf back to plain arrays
    d = dequantize_params(q)
    assert jax.tree.structure(d) == jax.tree.structure(params)
    np.testing.assert_allclose(
        np.asarray(d["conv_in"]["kernel"]),
        np.asarray(params["conv_in"]["kernel"]), atol=0.02)


def test_quantized_tree_save_load_equivalence(tmp_path):
    """Conversion + quantization runs once: the quantized tree round-trips
    through the flat .npz (payload + scales + dtype pair) bit-exactly."""
    params = init_unet_params(jax.random.PRNGKey(0), tiny_config())
    for mode in MODES:
        q = quantize_params(params, mode)
        path = str(tmp_path / f"q_{mode}.npz")
        save_params(path, q)
        back = load_params(path)
        assert jax.tree.structure(q) == jax.tree.structure(back)
        for a, b in zip(jax.tree.leaves(q), jax.tree.leaves(back)):
            assert a.dtype == b.dtype
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert params_nbytes(back) == params_nbytes(q)


# --------------------------------------------------------------------------
# end-to-end parity + bit-identity (UNet family here; DiT/MMDiT parity is
# pinned at the same tolerances by scripts/bench_weights.py in CI, and the
# families share one quantization path — models/weights.quantize_params)
# --------------------------------------------------------------------------


def test_unet_family_parity_and_none_bit_identity(devices8):
    kw = dict(batch_size=1, do_classifier_free_guidance=False)
    base, _ = build_sd_pipeline(devices8, 1, **kw)
    gen = lambda p: np.stack(  # noqa: E731
        p(["a cat"], num_inference_steps=2, seed=3, guidance_scale=1.0,
          output_type="np").images).astype(np.float64)
    ref = gen(base)
    # weight_quant="none" is bit-identical to a config that predates the knob
    again, _ = build_sd_pipeline(devices8, 1, weight_quant="none", **kw)
    np.testing.assert_array_equal(gen(again), ref)
    # int8 stays inside the pinned family tolerance
    q, _ = build_sd_pipeline(devices8, 1, weight_quant="int8", **kw)
    assert np.abs(gen(q) - ref).max() <= TOL["unet"]
    rep = q.weight_report()
    assert rep["weight_quant"] == "int8"
    assert rep["per_component_nbytes"]["denoiser"] * 1.7 <= (
        base.weight_report()["per_component_nbytes"]["denoiser"])
    # aux models were NOT quantized (separate sub-knob)
    assert rep["weight_quant_aux"] == "none"
    assert rep["per_component_nbytes"]["vae"] == (
        base.weight_report()["per_component_nbytes"]["vae"])


def test_set_weight_quant_matches_load_time_and_refuses_reverse(devices8):
    kw = dict(batch_size=1, do_classifier_free_guidance=False)
    load_time, _ = build_sd_pipeline(devices8, 1, weight_quant="int8", **kw)
    post, _ = build_sd_pipeline(devices8, 1, **kw)
    post.set_weight_quant("int8")
    gen = lambda p: np.stack(  # noqa: E731
        p(["a cat"], num_inference_steps=1, seed=5, guidance_scale=1.0,
          output_type="np").images)
    np.testing.assert_array_equal(gen(load_time), gen(post))
    # the dense kernels are gone: un-quantizing must refuse loudly
    with pytest.raises(ValueError, match="rebuild"):
        post.set_weight_quant("none")


def test_weight_quant_rejects_eager_sharding_parallelism(devices8):
    from distrifuser_tpu import DistriConfig

    with pytest.raises(ValueError, match="weight_quant"):
        DistriConfig(height=128, width=128, parallelism="tensor",
                     weight_quant="int8")
    # the post-construction hook enforces the SAME guard: the ladder must
    # not force-quantize a pre-sharded tensor-parallel tree
    pipe, _ = build_sd_pipeline(devices8, 1, parallelism="tensor",
                                batch_size=1)
    with pytest.raises(ValueError, match="parallelism"):
        pipe.set_weight_quant("int8")
    # through the serve policy hook the same refusal comes back typed, so
    # the retry loop can retract the ladder rung instead of retrying into
    # a deterministic wall
    from distrifuser_tpu.serve.errors import DegradationInapplicableError
    from distrifuser_tpu.serve.executors import apply_key_policy

    with pytest.raises(DegradationInapplicableError) as ei:
        apply_key_policy(pipe, key_for(weight_quant="int8"))
    assert ei.value.rung == RUNG_WEIGHT_QUANT


def test_quantized_npz_loads_into_quantized_pipeline(tmp_path, devices8):
    """The docs' restart story end to end: convert+quantize once, save,
    reload, hand the pre-quantized tree to a weight_quant='int8' pipeline
    — the constructor keeps the quantized leaves (idempotent) and the
    forward matches quantize-at-load bit for bit.  The archived compute
    dtype wins over load_params' dtype argument (the scales were baked
    against it)."""
    from distrifuser_tpu import DistriConfig
    from distrifuser_tpu.models.clip import init_clip_params, tiny_clip_config
    from distrifuser_tpu.models.unet import tiny_config as unet_tiny
    from distrifuser_tpu.models.vae import init_vae_params, tiny_vae_config
    from distrifuser_tpu.pipelines import DistriSDPipeline

    # archived compute dtype wins over load_params' dtype argument: the
    # WHOLE tree (dense leaves included) adopts it, and an explicit
    # mismatching dtype refuses
    bf16_kernel = {"kernel": jnp.ones((8, 4), jnp.bfloat16),
                   "bias": np.zeros((4,), np.float32)}
    dpath = str(tmp_path / "bf16_kernel.npz")
    save_params(dpath, quantize_params(bf16_kernel, "int8"))
    loaded = load_params(dpath)
    assert loaded["kernel"].dtype == jnp.bfloat16
    assert loaded["bias"].dtype == jnp.bfloat16
    with pytest.raises(ValueError, match="compute dtype"):
        load_params(dpath, jnp.float32)

    ucfg = unet_tiny(cross_attention_dim=32, sdxl=False)
    dense = init_unet_params(jax.random.PRNGKey(0), ucfg)
    path = str(tmp_path / "unet_int8.npz")
    save_params(path, quantize_params(dense, "int8"))
    reloaded = load_params(path)
    assert reloaded["conv_in"]["kernel"].dtype == jnp.float32  # archived

    def pipe_with(unet_params):
        cfg = DistriConfig(devices=devices8[:1], height=128, width=128,
                           warmup_steps=1, weight_quant="int8",
                           do_classifier_free_guidance=False, batch_size=1)
        tc = tiny_clip_config(hidden=32)
        return DistriSDPipeline.from_params(
            cfg, ucfg, unet_params, tiny_vae_config(),
            init_vae_params(jax.random.PRNGKey(1), tiny_vae_config()),
            [tc], [init_clip_params(jax.random.PRNGKey(2), tc)],
        )

    gen = lambda p: np.stack(  # noqa: E731
        p(["a cat"], num_inference_steps=1, seed=5, guidance_scale=1.0,
          output_type="np").images)
    np.testing.assert_array_equal(gen(pipe_with(reloaded)),
                                  gen(pipe_with(dense)))


# --------------------------------------------------------------------------
# serve: ExecKey separation + the weight_quant_on ladder rung
# --------------------------------------------------------------------------


def key_for(h=512, w=512, steps=4, **kw):
    kw.setdefault("model_id", "m")
    kw.setdefault("scheduler", "ddim")
    kw.setdefault("cfg", True)
    kw.setdefault("mesh_plan", "dp1.cfg1.sp1")
    return ExecKey(height=h, width=w, steps=steps, **kw)


def test_exec_key_weight_quant_identity_and_short():
    full = key_for()
    quant = dataclasses.replace(full, weight_quant="int8")
    assert full != quant and hash(full) != hash(quant)
    assert "wq-int8" in quant.short() and "wq" not in full.short()
    with pytest.raises(ValueError, match="weight_quant"):
        key_for(weight_quant="int4")


def test_ladder_rung_ordering_and_gate():
    cfg = ResilienceConfig(allow_weight_quant_on=True,
                           allow_bucket_fallback=True)
    lad = DegradationLadder(cfg, buckets=((512, 512), (1024, 1024)))
    st = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    k = key_for(1024, 1024)
    order = []
    for _ in range(6):
        rung = lad.next_rung(st, "oom", k, batch_size=1)
        if rung is None:
            break
        st.rungs.append(rung)
        order.append(rung)
    # weight_quant_on sits between stepwise and the contract-changing
    # bucket fallback (it changes numerics within tolerance, not shape)
    assert order.index("stepwise_fallback") < order.index(RUNG_WEIGHT_QUANT)
    assert order.index(RUNG_WEIGHT_QUANT) < order.index("bucket_fallback")
    dk = lad.apply(k, st.rungs)
    assert dk.weight_quant == "int8"
    # OFF by default: the first rung whose outputs change is opt-in
    lad_default = DegradationLadder(ResilienceConfig(), buckets=())
    st2 = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    st2.rungs.extend(["staging_off", "step_cache_off", "stepwise_fallback"])
    assert lad_default.next_rung(st2, "oom", k, batch_size=1) is None
    # already-quantized keys have nothing to give back on this rung
    lad_on = DegradationLadder(cfg, buckets=())
    st3 = KeyResilience(breaker=CircuitBreaker(3, 1.0))
    st3.rungs.extend(["staging_off", "step_cache_off", "stepwise_fallback"])
    qk = dataclasses.replace(k, weight_quant="int8")
    assert lad_on.next_rung(st3, "oom", qk, batch_size=1) is None


def test_server_oom_ladder_lands_on_quantized_key_both_executors_resident():
    """Acceptance (ISSUE 6): one server holds a full-precision AND a
    quantized executor for the SAME bucket under distinct ExecKeys — the
    OOM ladder switches the key onto weight_quant_on, and the fleet's
    weight ledger reports both programs' bytes."""
    DENSE, QUANT = 1_000_000, 540_000
    built = []

    class LedgerFake(FakeExecutor):
        def __init__(self, key, **kw):
            super().__init__(key, **kw)
            self.weight_nbytes = QUANT if key.weight_quant == "int8" else DENSE
            self.oomed = False

        def __call__(self, prompts, negatives, gs, seeds):
            # the dense program OOMs once at execute time (fragmented HBM);
            # the quantized rebuild fits
            if self.key.weight_quant == "none" and not self.oomed:
                self.oomed = True
                raise InjectedResourceExhausted("RESOURCE_EXHAUSTED: HBM")
            return super().__call__(prompts, negatives, gs, seeds)

    def factory(key):
        built.append(key)
        return LedgerFake(key, batch_size=4)

    cfg = ServeConfig(
        max_queue_depth=16, max_batch_size=1, batch_window_s=0.05,
        buckets=((512, 512),), default_steps=4,
        resilience=ResilienceConfig(
            max_retries=4, backoff_base_s=0.001, backoff_max_s=0.002,
            backoff_jitter=0.0, allow_weight_quant_on=True,
            allow_staging_off=False, allow_step_cache_off=False,
            allow_stepwise_fallback=False, allow_batch_split=False,
        ),
    )
    with InferenceServer(factory, cfg) as server:
        r = server.submit("p", height=512, width=512, seed=1).result(timeout=30)
        # the ladder invalidated the poisoned dense program; the operator
        # re-admits it through the fleet's public cache surface once the
        # HBM pressure passes — both executables now coexist
        server.cache.get(built[0])
        snap = server.metrics_snapshot()
        health = server.health()
    assert r.degradations == (RUNG_WEIGHT_QUANT,)
    wq = [k.weight_quant for k in built]
    assert wq == ["none", "int8", "none"]
    assert built[0] == dataclasses.replace(built[1], weight_quant="none")
    # both executables coexist in the fleet, under distinct short() tags,
    # and the weight ledger shows the quantized program is the smaller one
    ledger = snap["weights"]["per_executor_nbytes"]
    assert ledger == {built[0].short(): DENSE, built[1].short(): QUANT}
    assert snap["requests"]["degraded_" + RUNG_WEIGHT_QUANT] == 1
    assert health["status"] == "degraded"


def test_ladder_retracts_weight_quant_rung_builder_cannot_quantize():
    """A transient OOM must not become a permanently failing key when the
    builder can never quantize (tensor/pipefusion parallelism): the
    quantized rebuild's DegradationInapplicableError retracts the
    weight_quant_on rung, pins it inapplicable so the ladder never
    re-picks it, and the request still completes at full precision."""
    from distrifuser_tpu.serve.errors import DegradationInapplicableError

    built = []

    class OnceOOMFake(FakeExecutor):
        def __init__(self, key, **kw):
            super().__init__(key, **kw)
            self.oomed = False

        def __call__(self, prompts, negatives, gs, seeds):
            if not self.oomed:
                self.oomed = True
                raise InjectedResourceExhausted("RESOURCE_EXHAUSTED: HBM")
            return super().__call__(prompts, negatives, gs, seeds)

    def factory(key):
        built.append(key)
        if key.weight_quant != "none":
            # what executors.apply_key_policy raises for a tensor/
            # pipefusion pipeline (pre-sharded kernels cannot quantize)
            raise DegradationInapplicableError(
                "weight_quant does not apply to parallelism='tensor'",
                rung=RUNG_WEIGHT_QUANT)
        return OnceOOMFake(key, batch_size=4)

    cfg = ServeConfig(
        max_queue_depth=16, max_batch_size=1, batch_window_s=0.05,
        buckets=((512, 512),), default_steps=4,
        resilience=ResilienceConfig(
            max_retries=5, backoff_base_s=0.001, backoff_max_s=0.002,
            backoff_jitter=0.0, allow_weight_quant_on=True,
            allow_staging_off=False, allow_step_cache_off=False,
            allow_stepwise_fallback=False, allow_batch_split=False,
        ),
    )
    with InferenceServer(factory, cfg) as server:
        r = server.submit("p", height=512, width=512, seed=1).result(timeout=30)
        snap = server.metrics_snapshot()
    # the retracted rung no longer degrades the request...
    assert r.degradations == ()
    wq = [k.weight_quant for k in built]
    assert wq == ["none", "int8", "none"]
    assert snap["requests"]["degradation_retracted_" + RUNG_WEIGHT_QUANT] == 1
    # ...and is pinned inapplicable in the health surface so the ladder
    # never re-picks it for this key
    degr = snap["resilience"]["degradations"]
    assert [e["inapplicable"] for e in degr.values()] == [[RUNG_WEIGHT_QUANT]]
    assert all(e["rungs"] == [] for e in degr.values())


def test_apply_key_policy_quantizes_full_precision_builder(devices8):
    """serve.executors.apply_key_policy force-quantizes a builder that
    ignored ExecKey.weight_quant (the ladder rung depends on it), and the
    executor reports quantized weight bytes + the shrunk program parity."""
    from distrifuser_tpu.serve.executors import pipeline_executor_factory

    def build(key: ExecKey):
        pipe, _ = build_sd_pipeline(
            devices8, 1, height=key.height, width=key.width, batch_size=1,
            do_classifier_free_guidance=False,
        )
        return pipe  # builder ignores key.weight_quant entirely

    factory = pipeline_executor_factory(build)
    key = ExecKey(model_id="t", scheduler="ddim", height=128, width=128,
                  steps=1, cfg=False, mesh_plan="dp1.cfg1.sp1")
    dense = factory(key)
    quant = factory(dataclasses.replace(key, weight_quant="int8"))
    assert dense.pipeline.distri_config.weight_quant == "none"
    assert quant.pipeline.distri_config.weight_quant == "int8"
    assert quant.weight_nbytes * 1.7 <= dense.weight_nbytes
    a = dense(["a cat"], [""], 1.0, seeds=[3])
    b = quant(["a cat"], [""], 1.0, seeds=[3])
    assert np.abs(np.asarray(a[0], np.float64)
                  - np.asarray(b[0], np.float64)).max() <= TOL["unet"]

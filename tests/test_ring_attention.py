"""Ring attention vs the gather-layout oracle (sync and displaced phases)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from distrifuser_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.ops.attention import attention
from distrifuser_tpu.ops.ring_attention import ring_self_attention
from distrifuser_tpu.parallel.context import PHASE_STALE, PHASE_SYNC, PatchContext
from distrifuser_tpu.parallel.runner import DenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.utils.config import SP_AXIS


def sp_mesh(devices, n):
    return Mesh(np.array(devices[:n]).reshape(n), axis_names=(SP_AXIS,))


def attn_params(key, c):
    keys = jax.random.split(key, 4)
    return {
        "to_q": {"kernel": jax.random.normal(keys[0], (c, c)) * 0.3},
        "to_kv": {"kernel": jax.random.normal(keys[1], (c, 2 * c)) * 0.3},
        "to_out": {
            "kernel": jax.random.normal(keys[2], (c, c)) * 0.3,
            "bias": jax.random.normal(keys[3], (c,)) * 0.1,
        },
    }


@pytest.mark.parametrize("n,heads", [(2, 2), (4, 1), (8, 4)])
def test_ring_sync_matches_dense(devices8, n, heads):
    c = heads * 8
    b, l = 2, 6
    mesh = sp_mesh(devices8, n)
    p = attn_params(jax.random.PRNGKey(0), c)
    x = jax.random.normal(jax.random.PRNGKey(1), (b, l * n, c))
    dense = attention(p, x, heads=heads)

    def f(xl):
        ctx = PatchContext(n=n, mode="full_sync", phase=PHASE_SYNC, attn_impl="ring")
        return ring_self_attention(p, xl, ctx, "attn", heads=heads)

    y = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P(None, SP_AXIS), out_specs=P(None, SP_AXIS))
    )(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), atol=2e-4)


def test_ring_stale_matches_gather_stale(devices8):
    """Displaced phase: ring must reproduce the gather layout's stale output
    with an O(L/n) state (own chunk only)."""
    from distrifuser_tpu.ops.attention import patch_self_attention

    n, heads, b, l = 4, 2, 1, 4
    c = heads * 8
    mesh = sp_mesh(devices8, n)
    p = attn_params(jax.random.PRNGKey(2), c)
    x1 = jax.random.normal(jax.random.PRNGKey(3), (b, l * n, c))
    x2 = jax.random.normal(jax.random.PRNGKey(4), (b, l * n, c))

    def run(fn_name, impl):
        def sync(xl):
            ctx = PatchContext(n=n, mode="corrected_async_gn", phase=PHASE_SYNC,
                               attn_impl=impl)
            fn = ring_self_attention if impl == "ring" else patch_self_attention
            y = fn(p, xl, ctx, "attn", heads=heads)
            return y, ctx.state_out["attn"]

        y1, st = jax.jit(
            shard_map(sync, mesh=mesh, in_specs=P(None, SP_AXIS),
                      out_specs=(P(None, SP_AXIS), P(SP_AXIS)) if impl == "ring"
                      else (P(None, SP_AXIS), P()), check_vma=False)
        )(x1)

        def stale(xl, st):
            ctx = PatchContext(n=n, mode="corrected_async_gn", phase=PHASE_STALE,
                               attn_impl=impl, state_in={"attn": st})
            fn = ring_self_attention if impl == "ring" else patch_self_attention
            return fn(p, xl, ctx, "attn", heads=heads)

        st_spec = P(SP_AXIS) if impl == "ring" else P()
        y2 = jax.jit(
            shard_map(stale, mesh=mesh, in_specs=(P(None, SP_AXIS), st_spec),
                      out_specs=P(None, SP_AXIS), check_vma=False)
        )(x2, st)
        return np.asarray(y2), st

    y_ring, st_ring = run("ring", "ring")
    y_gather, st_gather = run("gather", "gather")
    np.testing.assert_allclose(y_ring, y_gather, atol=2e-4)
    # ring state is sharded over sp (per-device = global/n); gather state is
    # the full gathered KV replicated on every device -> n x more memory
    ring_per_device = st_ring.size // n
    gather_per_device = st_gather.size
    assert gather_per_device == n * ring_per_device


def test_ring_end_to_end_runner(devices8):
    ucfg = tiny_config()
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    out = {}
    for impl in ("gather", "ring"):
        cfg = DistriConfig(
            devices=devices8, height=128, width=128, warmup_steps=1,
            attn_impl=impl,
        )
        runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
        lat = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16, 4))
        enc = jax.random.normal(jax.random.PRNGKey(2), (2, 1, 7, ucfg.cross_attention_dim))
        out[impl] = np.asarray(runner.generate(lat, enc, num_inference_steps=4))
    np.testing.assert_allclose(out["ring"], out["gather"], atol=1e-3)


def test_ring_no_sync_mode_traces(devices8):
    """Regression: ring + no_sync must keep the scan carry structure stable
    (no attn-only state emission in the steady state)."""
    ucfg = tiny_config()
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    cfg = DistriConfig(
        devices=devices8, height=128, width=128, warmup_steps=1,
        mode="no_sync", attn_impl="ring",
    )
    runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    lat = jax.random.normal(jax.random.PRNGKey(1), (1, 16, 16, 4))
    enc = jax.random.normal(jax.random.PRNGKey(2), (2, 1, 7, ucfg.cross_attention_dim))
    out = runner.generate(lat, enc, num_inference_steps=4)
    assert np.isfinite(np.asarray(out)).all()


def test_attn_impl_validation(devices8):
    with pytest.raises(ValueError, match="attn_impl"):
        DistriConfig(devices=devices8, attn_impl="bogus")


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

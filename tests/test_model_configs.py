"""Config-driven architecture: deriving model configs from snapshot JSON.

The reference gets its architectures from diffusers `from_pretrained`, which
reads each component's config.json (/root/reference/distrifuser/pipelines.py:
30-42).  `unet_config_from_json` / `clip_config_from_json` /
`vae_config_from_json` replicate that here, so SD 1.x, SD 2.x (ViT-H text
encoder, v-prediction) and SDXL snapshots all load with their true
architecture.  The config dicts below are the actual fields of the published
snapshots' config.json files.
"""

import json
import os

import jax
import numpy as np
import pytest

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models import clip as clip_mod
from distrifuser_tpu.models import unet as unet_mod
from distrifuser_tpu.models import vae as vae_mod

SD15_UNET_JSON = {
    "_class_name": "UNet2DConditionModel",
    "attention_head_dim": 8,
    "block_out_channels": [320, 640, 1280, 1280],
    "cross_attention_dim": 768,
    "down_block_types": ["CrossAttnDownBlock2D", "CrossAttnDownBlock2D",
                         "CrossAttnDownBlock2D", "DownBlock2D"],
    "flip_sin_to_cos": True,
    "freq_shift": 0,
    "in_channels": 4,
    "layers_per_block": 2,
    "norm_num_groups": 32,
    "out_channels": 4,
    "up_block_types": ["UpBlock2D", "CrossAttnUpBlock2D",
                       "CrossAttnUpBlock2D", "CrossAttnUpBlock2D"],
}

SD21_UNET_JSON = {
    "_class_name": "UNet2DConditionModel",
    "attention_head_dim": [5, 10, 20, 20],
    "block_out_channels": [320, 640, 1280, 1280],
    "cross_attention_dim": 1024,
    "down_block_types": ["CrossAttnDownBlock2D", "CrossAttnDownBlock2D",
                         "CrossAttnDownBlock2D", "DownBlock2D"],
    "dual_cross_attention": False,
    "in_channels": 4,
    "layers_per_block": 2,
    "norm_num_groups": 32,
    "only_cross_attention": False,
    "out_channels": 4,
    "up_block_types": ["UpBlock2D", "CrossAttnUpBlock2D",
                       "CrossAttnUpBlock2D", "CrossAttnUpBlock2D"],
    "upcast_attention": True,
    "use_linear_projection": True,
}

SDXL_UNET_JSON = {
    "_class_name": "UNet2DConditionModel",
    "addition_embed_type": "text_time",
    "addition_time_embed_dim": 256,
    "attention_head_dim": [5, 10, 20],
    "block_out_channels": [320, 640, 1280],
    "cross_attention_dim": 2048,
    "down_block_types": ["DownBlock2D", "CrossAttnDownBlock2D",
                         "CrossAttnDownBlock2D"],
    "in_channels": 4,
    "layers_per_block": 2,
    "norm_num_groups": 32,
    "out_channels": 4,
    "projection_class_embeddings_input_dim": 2816,
    "transformer_layers_per_block": [1, 2, 10],
    "up_block_types": ["CrossAttnUpBlock2D", "CrossAttnUpBlock2D",
                       "UpBlock2D"],
    "use_linear_projection": True,
}


def test_unet_config_from_json_matches_presets():
    assert unet_mod.unet_config_from_json(SD15_UNET_JSON) == unet_mod.sd15_config()
    assert unet_mod.unet_config_from_json(SD21_UNET_JSON) == unet_mod.sd21_config()
    assert unet_mod.unet_config_from_json(SDXL_UNET_JSON) == unet_mod.sdxl_config()


def test_unet_config_from_json_scalar_broadcast():
    cfg = unet_mod.unet_config_from_json(SD15_UNET_JSON)
    assert cfg.num_attention_heads == (8, 8, 8, 8)  # scalar head count
    assert cfg.transformer_layers_per_block == (1, 1, 1, 1)  # absent -> 1s


def test_unet_config_from_json_rejects_unsupported():
    bad = dict(SD15_UNET_JSON, class_embed_type="projection")
    with pytest.raises(NotImplementedError, match="class_embed_type"):
        unet_mod.unet_config_from_json(bad)
    bad = dict(SD15_UNET_JSON, down_block_types=["AttnDownBlock2D"] * 4)
    with pytest.raises(NotImplementedError, match="block types"):
        unet_mod.unet_config_from_json(bad)
    bad = dict(SD15_UNET_JSON, addition_embed_type="image_time")
    with pytest.raises(NotImplementedError, match="addition_embed_type"):
        unet_mod.unet_config_from_json(bad)
    # diffusers re-saves disabled flags as per-block false lists — supported
    ok = dict(SD21_UNET_JSON, only_cross_attention=[False] * 4,
              dual_cross_attention=[False] * 4)
    assert unet_mod.unet_config_from_json(ok) == unet_mod.sd21_config()
    # LCM-distilled guidance embedding: loading would silently drop weights
    bad = dict(SD15_UNET_JSON, time_cond_proj_dim=256)
    with pytest.raises(NotImplementedError, match="time_cond_proj_dim"):
        unet_mod.unet_config_from_json(bad)
    bad = dict(SD15_UNET_JSON, mid_block_type="UNetMidBlock2DSimpleCrossAttn")
    with pytest.raises(NotImplementedError, match="mid_block_type"):
        unet_mod.unet_config_from_json(bad)


def test_unet_config_from_json_head_default():
    """diffusers defaults attention_head_dim=8 when both head fields are
    absent — a stripped config must load, not KeyError."""
    minimal = {k: v for k, v in SD15_UNET_JSON.items()
               if k != "attention_head_dim"}
    cfg = unet_mod.unet_config_from_json(minimal)
    assert cfg.num_attention_heads == (8, 8, 8, 8)


def test_clip_config_from_json():
    # SD1.x/SDXL text_encoder: ViT-L saved as plain CLIPTextModel — the
    # projection_dim field is present but must NOT be honored
    vit_l = {
        "architectures": ["CLIPTextModel"], "hidden_act": "quick_gelu",
        "hidden_size": 768, "intermediate_size": 3072,
        "max_position_embeddings": 77, "num_attention_heads": 12,
        "num_hidden_layers": 12, "projection_dim": 768, "vocab_size": 49408,
        "eos_token_id": 49407,
    }
    assert clip_mod.clip_config_from_json(vit_l) == clip_mod.clip_vit_l_config()

    # SD2.x text_encoder: OpenCLIP ViT-H, 23 stored layers, GeLU
    vit_h = {
        "architectures": ["CLIPTextModel"], "hidden_act": "gelu",
        "hidden_size": 1024, "intermediate_size": 4096,
        "max_position_embeddings": 77, "num_attention_heads": 16,
        "num_hidden_layers": 23, "projection_dim": 512, "vocab_size": 49408,
        "eos_token_id": 49407,
    }
    assert clip_mod.clip_config_from_json(vit_h) == clip_mod.open_clip_vith_config()

    # SDXL text_encoder_2: bigG WithProjection — projection IS honored
    bigg = {
        "architectures": ["CLIPTextModelWithProjection"], "hidden_act": "gelu",
        "hidden_size": 1280, "intermediate_size": 5120,
        "max_position_embeddings": 77, "num_attention_heads": 20,
        "num_hidden_layers": 32, "projection_dim": 1280, "vocab_size": 49408,
        "eos_token_id": 49407,
    }
    assert clip_mod.clip_config_from_json(bigg) == clip_mod.open_clip_bigg_config()


def test_vae_config_from_json():
    sdxl_vae = {
        "_class_name": "AutoencoderKL", "block_out_channels": [128, 256, 512, 512],
        "in_channels": 3, "latent_channels": 4, "layers_per_block": 2,
        "norm_num_groups": 32, "out_channels": 3, "scaling_factor": 0.13025,
    }
    assert vae_mod.vae_config_from_json(sdxl_vae) == vae_mod.sdxl_vae_config()
    sd_vae = dict(sdxl_vae, scaling_factor=0.18215)
    assert vae_mod.vae_config_from_json(sd_vae) == vae_mod.sd_vae_config()


# ---------------------------------------------------------------------------
# end-to-end: from_pretrained derives the architecture from a snapshot
# ---------------------------------------------------------------------------


def _write_safetensors(path, tree, invert):
    from safetensors.numpy import save_file

    sd = {}
    invert(tree, "", sd)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    save_file({k: np.ascontiguousarray(v) for k, v in sd.items()}, path)


def test_sd_from_pretrained_is_config_driven(tmp_path):
    """A fabricated SD2.1-style snapshot (linear projections, GeLU text
    encoder, v-prediction scheduler) must load with exactly that
    architecture — not the hardcoded SD1.5 preset."""
    torch = pytest.importorskip("torch")
    import transformers

    from test_weights_roundtrip import invert_tree

    root = tmp_path / "snap"
    # tiny SD2.1-flavored UNet: linear projections ON (sd15 preset has OFF)
    unet_json = {
        "_class_name": "UNet2DConditionModel",
        "attention_head_dim": [2, 4],
        "block_out_channels": [32, 64],
        "cross_attention_dim": 32,
        "down_block_types": ["DownBlock2D", "CrossAttnDownBlock2D"],
        "in_channels": 4, "layers_per_block": 1, "norm_num_groups": 8,
        "out_channels": 4,
        "up_block_types": ["CrossAttnUpBlock2D", "UpBlock2D"],
        "use_linear_projection": True,
    }
    ucfg = unet_mod.unet_config_from_json(unet_json)
    # structurally the tiny test architecture (embed-dim defaults aside)
    assert ucfg.block_out_channels == (32, 64)
    assert ucfg.num_attention_heads == (2, 4)
    params = unet_mod.init_unet_params(jax.random.PRNGKey(0), ucfg)
    _write_safetensors(
        str(root / "unet" / "diffusion_pytorch_model.safetensors"),
        params, invert_tree,
    )
    (root / "unet" / "config.json").write_text(json.dumps(unet_json))

    # tiny VAE
    vae_json = {
        "_class_name": "AutoencoderKL", "block_out_channels": [16, 32],
        "in_channels": 3, "latent_channels": 4, "layers_per_block": 1,
        "norm_num_groups": 8, "out_channels": 3, "scaling_factor": 0.9,
    }
    vcfg = vae_mod.vae_config_from_json(vae_json)
    vae_params = vae_mod.init_vae_params(jax.random.PRNGKey(1), vcfg)
    _write_safetensors(
        str(root / "vae" / "diffusion_pytorch_model.safetensors"),
        vae_params, invert_tree,
    )
    (root / "vae" / "config.json").write_text(json.dumps(vae_json))

    # tiny GeLU text encoder via transformers (ViT-H style act)
    hf_cfg = transformers.CLIPTextConfig(
        vocab_size=1000, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=77, hidden_act="gelu",
        eos_token_id=999, bos_token_id=998,
    )
    torch.manual_seed(0)
    te = transformers.CLIPTextModel(hf_cfg).eval()
    from safetensors.torch import save_file as save_torch

    os.makedirs(root / "text_encoder", exist_ok=True)
    save_torch(dict(te.state_dict()), str(root / "text_encoder" / "model.safetensors"))
    (root / "text_encoder" / "config.json").write_text(
        json.dumps(dict(hf_cfg.to_dict(), architectures=["CLIPTextModel"]))
    )

    os.makedirs(root / "scheduler", exist_ok=True)
    (root / "scheduler" / "scheduler_config.json").write_text(
        json.dumps({"_class_name": "DDIMScheduler",
                    "prediction_type": "v_prediction",
                    "num_train_timesteps": 1000})
    )

    from distrifuser_tpu.pipelines import DistriSDPipeline

    dcfg = DistriConfig(devices=jax.devices("cpu")[:2], height=64, width=64,
                        warmup_steps=1)
    pipe = DistriSDPipeline.from_pretrained(dcfg, str(root))
    # architecture came from the JSON, not the sd15 preset
    assert pipe.unet_config == ucfg
    assert pipe.unet_config.use_linear_projection is True
    assert pipe.vae_config.scaling_factor == 0.9
    tcfg = pipe.text_encoders[0][0]
    assert tcfg.hidden_act == "gelu" and tcfg.projection_dim is None
    assert pipe.scheduler.prediction_type == "v_prediction"

    out = pipe(prompt="a photo", num_inference_steps=2, guidance_scale=5.0,
               seed=0, output_type="latent")
    lat = np.asarray(out.images[0])
    assert lat.shape == (8, 8, 4)
    assert np.isfinite(lat).all()

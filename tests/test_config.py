"""DistriConfig / mesh bootstrap tests.

Checks the rank-topology parity with the reference
(/root/reference/distrifuser/utils.py:68-109): CFG split halves the patch
axis, batch_idx/split_idx mapping, power-of-2 assertion, and latent geometry.
"""

import jax
import pytest

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.utils.config import CFG_AXIS, DP_AXIS, SP_AXIS


def make_config(devices, **kw):
    kw.setdefault("use_cuda_graph", False)
    return DistriConfig(devices=devices, **kw)


def test_cfg_split_topology(devices8):
    cfg = make_config(devices8)
    assert cfg.world_size == 8
    assert cfg.n_device_per_batch == 4
    assert cfg.mesh.shape == {DP_AXIS: 1, CFG_AXIS: 2, SP_AXIS: 4}
    # reference utils.py:98-109: ranks [0, n) are CFG branch 0, [n, 2n) branch 1
    assert [cfg.batch_idx(r) for r in range(8)] == [0, 0, 0, 0, 1, 1, 1, 1]
    assert [cfg.split_idx(r) for r in range(8)] == [0, 1, 2, 3, 0, 1, 2, 3]
    # mesh device order matches that rank layout
    flat = list(cfg.mesh.devices.flat)
    assert flat == list(devices8)


def test_no_cfg_split(devices8):
    cfg = make_config(devices8, do_classifier_free_guidance=False)
    assert cfg.n_device_per_batch == 8
    assert cfg.mesh.shape == {DP_AXIS: 1, CFG_AXIS: 1, SP_AXIS: 8}
    assert cfg.batch_idx(5) == 0

    cfg2 = make_config(devices8, split_batch=False)
    assert cfg2.n_device_per_batch == 8


def test_single_device():
    cfg = make_config([jax.devices()[0]])
    assert cfg.world_size == 1
    assert cfg.n_device_per_batch == 1
    assert cfg.mesh.shape == {DP_AXIS: 1, CFG_AXIS: 1, SP_AXIS: 1}


def test_power_of_two_asserted(devices8):
    with pytest.raises(AssertionError):
        make_config(devices8[:3])


def test_validation(devices8):
    with pytest.raises(ValueError):
        make_config(devices8, mode="bogus")
    with pytest.raises(ValueError):
        make_config(devices8, parallelism="bogus")
    with pytest.raises(ValueError):
        make_config(devices8, split_scheme="bogus")
    with pytest.raises(ValueError):
        make_config(devices8, height=1001)  # not a multiple of 8


def test_latent_geometry(devices8):
    cfg = make_config(devices8, height=1024, width=1024)
    assert cfg.latent_height == 128 and cfg.latent_width == 128
    assert cfg.patch_height() == 32  # 128 rows / 4 sp devices
    assert cfg.patch_height(scale=4) == 8


def test_axon_backend_classifies_as_tpu(monkeypatch):
    """The axon PJRT plugin registers its backend under the name "axon"
    (jax_platforms="axon,cpu"); dtype selection keys on the platform CLASS,
    so axon must normalize to tpu — before this, DistriConfig silently
    defaulted to float32 on the real chip (2x bf16's HBM bytes)."""
    import jax.numpy as jnp

    from distrifuser_tpu.utils import env

    for plugin_name, want in [("axon", "tpu"), ("tpu", "tpu"), ("cpu", "cpu")]:
        monkeypatch.setattr(jax, "default_backend", lambda p=plugin_name: p)
        assert env.default_backend() == want

    monkeypatch.setattr(jax, "default_backend", lambda: "axon")
    cfg = DistriConfig(devices=jax.devices()[:1], use_cuda_graph=False)
    assert cfg.dtype == jnp.bfloat16


def test_pipefusion_accepts_first_class_knobs(devices8):
    """PR 7 (ROADMAP item 2): the knobs DistriConfig used to reject for
    parallelism='pipefusion' — comm_compress, weight_quant, the step
    cache, and the new pipe_patches — all construct; the step cache still
    pairs its knobs, and weight_quant still rejects tensor parallelism."""
    cfg = DistriConfig(
        devices=devices8[:2], height=128, width=128,
        parallelism="pipefusion", comm_compress="int8_residual",
        step_cache_interval=2, step_cache_depth=1, weight_quant="int8",
        pipe_patches=4, use_cuda_graph=True,
    )
    assert cfg.step_cache_enabled and cfg.pipe_patches == 4


def test_pipe_patches_validation(devices8):
    with pytest.raises(ValueError, match="pipe_patches"):
        make_config(devices8[:2], pipe_patches=2)  # patch parallelism
    with pytest.raises(ValueError, match="pipe_patches"):
        make_config(devices8[:2], parallelism="pipefusion", pipe_patches=0)

"""Tensor parallelism vs dense oracle.

The reference's TP path was never testable (its CFG gather crashes,
distri_sdxl_unet_tp.py:160 — SURVEY.md §2.6); here TP is exact math, so the
oracle is strict: an n-way TP UNet forward must match the dense forward, with
non-divisible head counts (zero-padded shards) covered explicitly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from distrifuser_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models.unet import (
    DenseDispatch,
    UNetConfig,
    init_unet_params,
    tiny_config,
    unet_forward,
)
from distrifuser_tpu.models.unet_tp import (
    TPDispatch,
    head_dim_table,
    prepare_tp_params,
    tp_attention,
    _shard_attn,
)
from distrifuser_tpu.ops.attention import attention
from distrifuser_tpu.parallel.runner import make_runner
from distrifuser_tpu.schedulers import get_scheduler
from distrifuser_tpu.utils.config import SP_AXIS


def sp_mesh(devices, n):
    return Mesh(np.array(devices[:n]).reshape(n), axis_names=(SP_AXIS,))


@pytest.mark.parametrize("heads,n", [(4, 4), (5, 4), (2, 8)])
def test_tp_attention_matches_dense_with_head_padding(devices8, heads, n):
    c = heads * 8  # head_dim 8
    mesh = sp_mesh(devices8, n)
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    p = {
        "to_q": {"kernel": jax.random.normal(keys[0], (c, c)) * 0.3},
        "to_kv": {"kernel": jax.random.normal(keys[1], (c, 2 * c)) * 0.3},
        "to_out": {
            "kernel": jax.random.normal(keys[2], (c, c)) * 0.3,
            "bias": jax.random.normal(keys[3], (c,)) * 0.1,
        },
    }
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 10, c))
    dense = attention(p, x, heads=heads)

    tp_p, spec = _shard_attn(p, heads, n)
    y = jax.jit(
        shard_map(
            lambda pp, xx: tp_attention(pp, xx, head_dim=c // heads),
            mesh=mesh,
            in_specs=(spec, P()),
            out_specs=P(),
            check_vma=False,
        )
    )(tp_p, x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), atol=1e-4)


@pytest.mark.parametrize("n", [2, 4])
def test_tp_unet_matches_dense(devices8, n):
    ucfg = tiny_config(sdxl=False)
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    mesh = sp_mesh(devices8, n)
    key = jax.random.PRNGKey(1)
    sample = jax.random.normal(key, (1, 16, 16, ucfg.in_channels))
    enc = jax.random.normal(jax.random.fold_in(key, 1), (1, 7, ucfg.cross_attention_dim))
    t = jnp.array([3.0])

    dense = unet_forward(params, ucfg, sample, t, enc, dispatch=DenseDispatch())

    tp_params, specs = prepare_tp_params(params, ucfg, n)
    head_dims = head_dim_table(ucfg)

    def fwd(pp, s, e):
        d = TPDispatch(n, head_dims)
        return unet_forward(pp, ucfg, s, t, e, dispatch=d)

    y = jax.jit(
        shard_map(
            fwd, mesh=mesh, in_specs=(specs, P(), P()), out_specs=P(), check_vma=False
        )
    )(tp_params, sample, enc)
    np.testing.assert_allclose(np.asarray(y), np.asarray(dense), atol=2e-3)


def test_tp_runner_end_to_end(devices8):
    cfg = DistriConfig(
        devices=devices8[:4],
        height=128,
        width=128,
        parallelism="tensor",
        warmup_steps=1,
    )
    ucfg = tiny_config()
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    runner = make_runner(cfg, ucfg, params, get_scheduler("ddim"))
    lat = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 16, 4))
    enc = jax.random.normal(jax.random.PRNGKey(3), (2, 1, 7, ucfg.cross_attention_dim))
    out = runner.generate(lat, enc, num_inference_steps=3)
    assert np.isfinite(np.asarray(out)).all()

    # oracle: single-device run of the same generation
    cfg1 = DistriConfig(
        devices=devices8[:1], height=128, width=128, parallelism="tensor",
        warmup_steps=1,
    )
    runner1 = make_runner(cfg1, ucfg, params, get_scheduler("ddim"))
    out1 = runner1.generate(lat, enc, num_inference_steps=3)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out1), atol=2e-2)


def test_head_dim_table_covers_all_attn():
    ucfg = tiny_config()
    table = head_dim_table(ucfg)
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    # every attn in the tree must be in the table
    names = []

    def walk(tree, path):
        if isinstance(tree, dict):
            for k, v in tree.items():
                if k in ("attn1", "attn2"):
                    names.append(f"{path}.{k}")
                elif isinstance(v, (dict, list)):
                    walk(v, f"{path}.{k}" if path else k)
        else:
            for i, v in enumerate(tree):
                walk(v, f"{path}.{i}")

    walk(params, "")
    assert set(names) == set(table)


# CPU-compile-heavy module: the fake 8-device mesh compiles full
# multi-device denoise loops, minutes per test on the tier-1 CPU runner.
# Runs with `-m slow` and on real-hardware rounds.
pytestmark = pytest.mark.slow

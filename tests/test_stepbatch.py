"""Step-level continuous batching (distrifuser_tpu/serve/stepbatch.py):
slot-pool policy (EDF slack, cohort choice, preemption), the server's
step-granular scheduling round on the deterministic fakes, the
bit-identity pins (solo == joined-mid-flight == preempted-and-resumed,
fakes for all three families plus the real tiny SD config), progressive
previews, the controller's step-granular occupancy model, and the
serve_bench --continuous artifact."""

import time

import numpy as np
import pytest

from distrifuser_tpu.serve import (
    ExecKey,
    InferenceServer,
    ServeConfig,
    ServerClosedError,
    StepBatchConfig,
)
from distrifuser_tpu.serve.queue import Request, RequestQueue
from distrifuser_tpu.serve.stepbatch import SlotState, StepBatcher
from distrifuser_tpu.serve.testing import (
    FakeExecutorFactory,
    StepFakeExecutorFactory,
    fake_image,
    fake_preview,
)


def key_for(model="m", h=64, w=64, steps=4, exec_mode="step", **kw):
    return ExecKey(model_id=model, scheduler="ddim", height=h, width=w,
                   steps=steps, cfg=True, mesh_plan="dp1.cfg1.sp1",
                   exec_mode=exec_mode, **kw)


def step_config(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("slots", 4)
    return StepBatchConfig(**kw)


def serve_config(**kw):
    kw.setdefault("max_queue_depth", 32)
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("batch_window_s", 0.001)
    kw.setdefault("buckets", ((64, 64),))
    kw.setdefault("warmup_buckets", ())
    kw.setdefault("default_steps", 4)
    kw.setdefault("default_ttl_s", 60.0)
    kw.setdefault("step_batching", step_config())
    return ServeConfig(**kw)


def mk_request(prompt="p", steps=4, ttl=60.0, seed=0, now=None):
    now = time.monotonic() if now is None else now
    return Request(prompt=prompt, height=64, width=64,
                   num_inference_steps=steps, deadline=now + ttl,
                   seed=seed, enqueue_ts=now)


def mk_state(req, steps_total=None, **kw):
    k = key_for(steps=steps_total or req.num_inference_steps)
    kw.setdefault("base_key", k)
    kw.setdefault("ekey", k)
    kw.setdefault("executor", object())
    kw.setdefault("compile_hit", True)
    return SlotState(request=req, work={}, steps_total=k.steps, **kw)


# --------------------------------------------------------------------------
# config + key validation
# --------------------------------------------------------------------------


def test_step_batch_config_validates():
    with pytest.raises(ValueError, match="slots"):
        StepBatchConfig(slots=0)
    with pytest.raises(ValueError, match="preview_interval"):
        StepBatchConfig(preview_interval=-1)
    with pytest.raises(ValueError, match="step_service_prior_s"):
        StepBatchConfig(step_service_prior_s=0.0)
    with pytest.raises(ValueError, match="step_width"):
        StepBatchConfig(step_width=-1)


def test_step_batching_excludes_staging_and_pipefusion():
    with pytest.raises(ValueError, match="mutually exclusive"):
        serve_config(pipeline_stages=True)
    with pytest.raises(ValueError, match="patch-parallel"):
        serve_config(parallelism="pipefusion", pipe_patches=2)
    with pytest.raises(ValueError, match="patch-parallel"):
        serve_config(buckets=((64, 64), (128, 128)),
                     bucket_parallelism={(128, 128): "pipefusion"})


def test_exec_key_step_mode():
    k = key_for()
    assert ":step" in k.short()
    # step and stepwise keys must never collide to one ledger tag
    assert key_for(exec_mode="stepwise").short() != k.short()
    with pytest.raises(ValueError, match="pipefusion"):
        key_for(exec_mode="step", parallelism="pipefusion", pipe_patches=2)
    with pytest.raises(ValueError, match="exec_mode"):
        key_for(exec_mode="warp")


def test_stepwise_rung_never_applies_to_step_keys():
    """The ladder's stepwise_fallback is for FUSED keys — a step key
    already runs the per-step programs, so the rung must skip it."""
    from distrifuser_tpu.serve.resilience import (
        RUNG_STEPWISE,
        DegradationLadder,
    )
    from distrifuser_tpu.utils.config import ResilienceConfig

    ladder = DegradationLadder(ResilienceConfig(), buckets=((64, 64),))
    assert ladder._applicable(RUNG_STEPWISE, key_for(exec_mode="fused"))
    assert not ladder._applicable(RUNG_STEPWISE, key_for(exec_mode="step"))


def test_server_keys_buckets_at_step_mode():
    server = InferenceServer(StepFakeExecutorFactory(), serve_config())
    assert server._exec_key_for(64, 64, 4, cfg=True).exec_mode == "step"
    mono = InferenceServer(FakeExecutorFactory(),
                           serve_config(step_batching=StepBatchConfig()))
    assert mono._exec_key_for(64, 64, 4, cfg=True).exec_mode == "fused"


# --------------------------------------------------------------------------
# slot-pool policy (no server, injected clock)
# --------------------------------------------------------------------------


def test_slack_and_cohort_edf_order():
    clock = [100.0]
    sb = StepBatcher(step_config(slots=3, step_service_prior_s=0.1),
                     clock=lambda: clock[0])
    # 4 remaining steps x 0.1s = 0.4s predicted service
    tight = mk_state(mk_request("tight", ttl=0.5, now=100.0))
    loose = mk_state(mk_request("loose", ttl=5.0, now=100.0))
    sb.admit(loose)
    sb.admit(tight)
    assert sb.state_slack(tight, 100.0) == pytest.approx(0.1)
    assert sb.state_slack(loose, 100.0) == pytest.approx(4.6)
    assert [s.request.prompt for s in sb.cohort(100.0)] == ["tight",
                                                            "loose"]


def test_step_width_truncates_cohort():
    sb = StepBatcher(step_config(slots=4, step_width=2,
                                 step_service_prior_s=0.1),
                     clock=lambda: 0.0)
    for i, ttl in enumerate((5.0, 1.0, 3.0, 0.7)):
        sb.admit(mk_state(mk_request(f"r{i}", ttl=ttl, now=0.0)))
    cohort = sb.cohort(0.0)
    assert [s.request.prompt for s in cohort] == ["r3", "r1"]


def test_pick_victim_policy():
    sb = StepBatcher(step_config(slots=2, step_service_prior_s=0.1,
                                 preempt_margin_s=0.5),
                     clock=lambda: 0.0)
    tight = mk_state(mk_request("tight", ttl=0.6, now=0.0))   # slack 0.2
    loose = mk_state(mk_request("loose", ttl=9.0, now=0.0))   # slack 8.6
    sb.admit(tight)
    sb.admit(loose)
    v = sb.pick_victim(newcomer_slack=1.0, now=0.0)
    assert v is loose
    # margin: a victim barely better than the newcomer is not worth it
    assert sb.pick_victim(newcomer_slack=8.5, now=0.0) is None
    # no thrash: a once-preempted request is never parked again
    loose.preempts = 1
    assert sb.pick_victim(newcomer_slack=1.0, now=0.0) is None
    # preemption off => never a victim
    sb2 = StepBatcher(step_config(slots=1, allow_preemption=False),
                      clock=lambda: 0.0)
    sb2.admit(mk_state(mk_request("loose2", ttl=9.0, now=0.0)))
    assert sb2.pick_victim(newcomer_slack=0.0, now=0.0) is None


def test_park_unpark_remove_accounting():
    sb = StepBatcher(step_config(slots=2), clock=lambda: 0.0)
    a = mk_state(mk_request("a", now=0.0))
    b = mk_state(mk_request("b", now=0.0))
    sb.admit(a), sb.admit(b)
    assert sb.free_slots() == 0 and sb.joins == 2
    sb.park(a)
    assert sb.free_slots() == 1 and a.parked and a.preempts == 1
    assert sb.parked == [a] and sb.preempt_count == 1
    assert sb.remaining_steps_total() == 8  # parked still counts
    sb.unpark(a)
    assert sb.free_slots() == 0 and sb.resumes == 1 and sb.joins == 2
    sb.remove(a), sb.remove(b)
    assert sb.free_slots() == 2 and sb.leaves == 2
    snap = sb.snapshot()
    assert snap["occupied"] == 0 and snap["joins"] == 2
    assert snap["preempts"] == 1 and snap["resumes"] == 1


def test_per_step_estimate_sources():
    est = [None]
    sb = StepBatcher(step_config(step_service_prior_s=0.25),
                     clock=lambda: 0.0, step_estimate=lambda: est[0])
    assert sb.per_step_s() == 0.25            # prior
    sb.note_round(0.1)
    assert sb.per_step_s() == pytest.approx(0.1)   # EWMA
    est[0] = 0.05                             # controller calibration wins
    assert sb.per_step_s() == 0.05
    assert sb.snapshot()["round_s_mean"] == pytest.approx(0.1)


def test_queue_peek_best_and_remove():
    q = RequestQueue(8)
    now = time.monotonic()
    reqs = [mk_request(f"r{i}", ttl=ttl, now=now)
            for i, ttl in enumerate((5.0, 1.0, 3.0))]
    for r in reqs:
        q.put(r)
    assert q.peek_best(lambda r: r.deadline) is reqs[1]
    assert len(q) == 3  # peek never removes
    assert q.remove(reqs[1]) and not q.remove(reqs[1])
    assert q.peek_best(lambda r: r.deadline) is reqs[2]
    assert q.remove(reqs[2]) and q.remove(reqs[0])
    assert len(q) == 0
    assert q.peek_best(lambda r: r.deadline) is None


# --------------------------------------------------------------------------
# end-to-end on the fakes: scheduling, previews, preemption, stop
# --------------------------------------------------------------------------


def test_continuous_server_completes_request_shaped():
    fac = StepFakeExecutorFactory(batch_size=4, step_time_s=0.002)
    with InferenceServer(fac, serve_config()) as server:
        futs = [server.submit(f"p{i}", height=64, width=64, seed=i)
                for i in range(6)]
        results = [f.result(timeout=30) for f in futs]
    key = fac.built[0]
    assert key.exec_mode == "step"
    for i, r in enumerate(results):
        assert r.batch_size == 1
        assert ":step" in r.exec_key
        assert np.array_equal(r.output, fake_image(f"p{i}", i, key))
    snap = server.metrics_snapshot()
    sb = snap["step_batching"]
    assert sb["joins"] == 6 and sb["leaves"] == 6
    assert snap["requests"]["completed"] == 6
    assert snap["requests"]["steps_executed"] == 6 * 4


@pytest.mark.parametrize("model", ["unet", "dit", "mmdit"])
def test_bit_identity_solo_vs_joined_fakes(model):
    """The correctness bar on all three families' fakes: a solo run and
    a joined-mid-flight run produce byte-equal images per (prompt, seed,
    steps) — and both equal the whole-batch server's output."""
    def run(submissions, stagger_s=0.0, continuous=True):
        cfg = serve_config() if continuous else serve_config(
            step_batching=StepBatchConfig())
        fac = (StepFakeExecutorFactory(batch_size=4, step_time_s=0.003)
               if continuous else
               FakeExecutorFactory(batch_size=4, step_time_s=0.003))
        with InferenceServer(fac, cfg, model_id=model) as server:
            futs = []
            for prompt, seed in submissions:
                futs.append(server.submit(prompt, height=64, width=64,
                                          seed=seed))
                if stagger_s:
                    time.sleep(stagger_s)  # join mid-flight
            return [f.result(timeout=30).output for f in futs]

    solo = run([("a cat", 7)])
    joined = run([("a cat", 7), ("a dog", 9), ("a fox", 11)],
                 stagger_s=0.004)
    whole = run([("a cat", 7)], continuous=False)
    np.testing.assert_array_equal(solo[0], joined[0])
    np.testing.assert_array_equal(solo[0], whole[0])
    # and the joiners got THEIR own images
    k = key_for(model=model, steps=4)
    np.testing.assert_array_equal(joined[1], fake_image("a dog", 9, k))
    np.testing.assert_array_equal(joined[2], fake_image("a fox", 11, k))


def test_previews_stream_and_ttfp_recorded():
    fac = StepFakeExecutorFactory(batch_size=4, step_time_s=0.002)
    cfg = serve_config(step_batching=step_config(preview_interval=2),
                       default_steps=6)
    seen = []
    with InferenceServer(fac, cfg) as server:
        f_on = server.submit("p", height=64, width=64, seed=1,
                             num_inference_steps=6,
                             on_progress=lambda s, t, img:
                             seen.append((s, t, img.copy())))
        f_off = server.submit("q", height=64, width=64, seed=2,
                              num_inference_steps=6)
        r_on, r_off = f_on.result(timeout=30), f_off.result(timeout=30)
    assert [s for s, _, _ in seen] == [2, 4, 6]
    assert all(t == 6 for _, t, _ in seen)
    key = fac.built[0]
    np.testing.assert_array_equal(seen[0][2], fake_preview("p", 1, key, 2))
    assert r_on.previews == 3
    assert r_on.first_preview_s is not None and r_on.first_preview_s > 0
    # no callback => no previews, and the result says so
    assert r_off.previews == 0 and r_off.first_preview_s is None
    snap = server.metrics_snapshot()
    assert snap["requests"]["step_previews"] == 3
    # the time-to-first-preview histogram saw exactly one sample
    hist = snap["latency_s"]  # whole-batch phases only; check registry
    fp = [w for lbls, w in server.registry.family("serve_latency_seconds")
          if lbls.get("phase") == "first_preview"]
    assert fp and fp[0].snapshot()["count"] == 1
    assert hist["e2e"]["count"] == 2


def test_preemption_parks_and_resumes_bit_identically():
    fac = StepFakeExecutorFactory(batch_size=4, step_time_s=0.005)
    cfg = serve_config(
        default_steps=30,
        step_batching=step_config(slots=1, step_service_prior_s=0.005))
    with InferenceServer(fac, cfg) as server:
        fa = server.submit("slack", height=64, width=64, seed=1,
                           ttl_s=60.0)
        deadline = time.monotonic() + 10
        while not server.stepbatch.occupied():
            assert time.monotonic() < deadline, "victim never admitted"
            time.sleep(0.002)
        time.sleep(0.02)  # let it make progress mid-denoise
        # needs 30 x 5ms = 150ms; waiting out the victim would miss
        fb = server.submit("tight", height=64, width=64, seed=2,
                           ttl_s=0.22)
        ra, rb = fa.result(timeout=30), fb.result(timeout=30)
    key = fac.built[0]
    assert ra.preempts == 1 and rb.preempts == 0
    ex = fac.executors[0]
    assert ex.park_calls == 1 and ex.resume_calls == 1
    snap = server.metrics_snapshot()["step_batching"]
    assert snap["preempts"] == 1 and snap["resumes"] == 1
    # the preempted-and-resumed image is byte-identical to solo
    np.testing.assert_array_equal(ra.output, fake_image("slack", 1, key))
    np.testing.assert_array_equal(rb.output, fake_image("tight", 2, key))


def test_cancelled_future_frees_slot():
    fac = StepFakeExecutorFactory(batch_size=4, step_time_s=0.005)
    cfg = serve_config(default_steps=40,
                       step_batching=step_config(slots=1,
                                                 allow_preemption=False))
    with InferenceServer(fac, cfg) as server:
        fa = server.submit("long", height=64, width=64, seed=1)
        deadline = time.monotonic() + 10
        while not server.stepbatch.occupied():
            assert time.monotonic() < deadline
            time.sleep(0.002)
        fb = server.submit("next", height=64, width=64, seed=2)
        fa.cancel()
        rb = fb.result(timeout=30)
    assert np.array_equal(rb.output,
                          fake_image("next", 2, fac.built[0]))
    assert server.metrics_snapshot()["requests"].get("step_cancelled",
                                                     0) == 1


def test_queued_deadline_rejected_not_executed():
    fac = StepFakeExecutorFactory(batch_size=4, step_time_s=0.01)
    cfg = serve_config(default_steps=20,
                       step_batching=step_config(slots=1))
    from distrifuser_tpu.serve import DeadlineExceededError

    with InferenceServer(fac, cfg) as server:
        server.submit("hog", height=64, width=64, seed=1)
        deadline = time.monotonic() + 10
        while not server.stepbatch.occupied():
            assert time.monotonic() < deadline
            time.sleep(0.002)
        # hopeless from birth (slack < 0): never preempts, never admits —
        # expires in the queue and is rejected, not executed
        doomed = server.submit("doomed", height=64, width=64, seed=2,
                               ttl_s=0.01)
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=30)
    assert server.metrics_snapshot()["requests"]["rejected_deadline"] == 1


def test_stop_resolves_every_resident_future():
    fac = StepFakeExecutorFactory(batch_size=4, step_time_s=0.01)
    cfg = serve_config(default_steps=200,
                       step_batching=step_config(slots=2))
    server = InferenceServer(fac, cfg).start(warmup=False)
    futs = [server.submit(f"p{i}", height=64, width=64, seed=i)
            for i in range(5)]
    deadline = time.monotonic() + 10
    while not server.stepbatch.occupied():
        assert time.monotonic() < deadline
        time.sleep(0.002)
    server.stop(timeout=30.0)
    for f in futs:
        with pytest.raises(ServerClosedError):
            f.result(timeout=5)
    assert not server.stepbatch.occupied()
    assert not server.stepbatch.parked


def test_step_failure_is_terminal_and_counted():
    class BoomStepFactory(StepFakeExecutorFactory):
        def _new_executor(self, key):
            ex = super()._new_executor(key)
            orig = ex.step_run

            def boom(works, _orig=orig, _ex=ex):
                if len(_ex.step_calls) >= 2:
                    raise RuntimeError("injected step failure")
                return _orig(works)

            ex.step_run = boom
            return ex

    from distrifuser_tpu.serve import ExecuteFailedError

    fac = BoomStepFactory(batch_size=4, step_time_s=0.001)
    with InferenceServer(fac, serve_config()) as server:
        f = server.submit("p", height=64, width=64, seed=1)
        with pytest.raises(ExecuteFailedError):
            f.result(timeout=30)
    snap = server.metrics_snapshot()
    assert snap["requests"]["failed_execute"] >= 1
    assert not server.stepbatch.occupied()


def test_watchdog_abandoned_step_defers_release():
    """A hung cohort step that the watchdog abandons must fail the
    members' futures immediately but DEFER the buffer release and the
    executor unpin until the orphaned worker drains — freeing either
    under the still-running thread would be a use-after-free (the
    staged pipeline's deferral protocol)."""
    import threading

    from distrifuser_tpu.serve import WatchdogTimeoutError
    from distrifuser_tpu.utils.config import ResilienceConfig

    hang = threading.Event()
    aborted = threading.Event()

    class HangStepFactory(StepFakeExecutorFactory):
        def _new_executor(self, key):
            ex = super()._new_executor(key)
            ex.step_run = lambda works: hang.wait(10)
            orig_abort = ex.step_abort
            ex.step_abort = lambda w: (aborted.set(), orig_abort(w))[1]
            return ex

    fac = HangStepFactory(batch_size=4)
    cfg = serve_config(
        resilience=ResilienceConfig(watchdog_timeout_s=0.05,
                                    max_retries=0))
    with InferenceServer(fac, cfg) as server:
        f = server.submit("p", height=64, width=64, seed=1)
        with pytest.raises(WatchdogTimeoutError):
            f.result(timeout=30)
        ex = fac.executors[0]
        # the future failed, but the orphaned worker still runs: the
        # executor stays pinned and the work is NOT aborted yet
        assert not aborted.wait(0.1), "buffers released under the worker"
        assert server.cache.pin_count(ex) >= 1
        hang.set()  # the worker drains -> deferred release fires
        deadline = time.monotonic() + 10
        while not aborted.is_set() or server.cache.pin_count(ex):
            assert time.monotonic() < deadline, "deferred release never ran"
            time.sleep(0.005)


def test_slo_snapshot_carries_step_block():
    fac = StepFakeExecutorFactory(batch_size=4, step_time_s=0.0)
    with InferenceServer(fac, serve_config()) as server:
        snap = server.slo_snapshot()
        assert snap["step"]["slots"] == 4
        assert snap["step"]["steps_hint"] == 4
        assert "per_step_s" in snap["step"]
    mono = InferenceServer(FakeExecutorFactory(),
                           serve_config(step_batching=StepBatchConfig()))
    assert "step" not in mono.slo_snapshot()


# --------------------------------------------------------------------------
# controller: step-granular occupancy accounting (the satellite fix)
# --------------------------------------------------------------------------


def _controller(clock, **kw):
    from distrifuser_tpu.serve.controller import SLOController
    from distrifuser_tpu.utils.config import ControllerConfig

    kw.setdefault("enabled", True)
    kw.setdefault("slo_p99_s", {"default": 0.5})
    kw.setdefault("escalate_cooldown_s", 0.0)
    kw.setdefault("service_prior_s", 0.4)
    return SLOController(ControllerConfig(**kw), clock=clock,
                         batch_hint=4)


def test_step_occupancy_prevents_over_escalation():
    """8 queued requests on a whole-batch server mean two more BATCH
    services of wait (escalate); on an 8-slot step server they amortize
    to one extra request's worth of steps across the pool — the
    step-granular term must keep the class at full quality where the
    whole-batch model would walk down."""
    now = [0.0]
    ctl_batch = _controller(lambda: now[0])
    ctl_step = _controller(lambda: now[0])
    base = {"queue_depth": 8, "inflight_requests": 0,
            "classes": {"default": {"p99": 0.0, "window": 0}}}
    step_block = {"slots": 8, "occupied": 8, "parked": 0,
                  # 8 queued x 4 steps + 16 in-pool = 48 backlog steps
                  "remaining_steps_total": 16, "per_step_s": 0.01,
                  "steps_hint": 4}
    for _ in range(3):
        now[0] += 1.0
        ctl_batch.poll(dict(base))
        ctl_step.poll({**base, "step": dict(step_block)})
    # whole-batch model: (1 + 2 batches) x 0.4s = 1.2s > 0.5 target
    assert ctl_batch.snapshot()["classes"]["default"]["tier"] > 0
    # step model: 0.01 x (4 + 48/8) = 0.1s <= 0.5 — no escalation
    assert ctl_step.snapshot()["classes"]["default"]["tier"] == 0


def test_step_occupancy_still_escalates_under_real_pressure():
    now = [0.0]
    ctl = _controller(lambda: now[0])
    snap = {"queue_depth": 64, "inflight_requests": 0,
            "classes": {"default": {"p99": 0.0, "window": 0}},
            "step": {"slots": 4, "occupied": 4, "parked": 0,
                     "remaining_steps_total": 16, "per_step_s": 0.05,
                     "steps_hint": 8}}
    now[0] += 1.0
    ctl.poll(snap)
    # 0.05 x (8 + (64x8 + 16)/4) = 7s >> 0.5 — the walk starts
    assert ctl.snapshot()["classes"]["default"]["tier"] == 1


def test_observe_step_calibration():
    ctl = _controller(lambda: 0.0)
    assert ctl.step_service_estimate() is None
    ctl.observe_step(1.0, 0.02)
    ctl.observe_step(0.5, 0.02)  # cheaper tier, same wall => 0.04 full-eq
    assert ctl.step_service_estimate() == pytest.approx(0.03)
    assert ctl.snapshot()["step_service_estimate_s"] == pytest.approx(0.03)


def test_server_feeds_controller_step_calibration():
    from distrifuser_tpu.utils.config import ControllerConfig

    fac = StepFakeExecutorFactory(batch_size=4, step_time_s=0.002)
    cfg = serve_config(
        controller=ControllerConfig(enabled=True,
                                    slo_p99_s={"default": 30.0}))
    with InferenceServer(fac, cfg) as server:
        server.submit("p", height=64, width=64, seed=1).result(timeout=30)
    est = server.controller.step_service_estimate()
    assert est is not None and est > 0


# --------------------------------------------------------------------------
# serve_bench --continuous artifact
# --------------------------------------------------------------------------


def test_serve_bench_continuous_artifact(tmp_path):
    import json
    import sys

    sys.path.insert(0, "scripts")
    import serve_bench

    out = tmp_path / "continuous.json"
    rc = serve_bench.main([
        "--dry-run", "--continuous", "--mode", "open", "--rate", "25",
        "--duration", "0.8", "--steps", "6", "--fake_build_s", "0",
        "--fake_step_s", "0.004", "--preview_interval", "2",
        "--slots", "4", "--out", str(out),
    ])
    assert rc == 0
    artifact = json.loads(out.read_text())
    assert artifact["bench"]["continuous_compare"] is True
    assert artifact["queue_wait_p99_ratio"] > 0
    cont = artifact["continuous"]
    assert cont["metrics"]["step_batching"]["joins"] > 0
    assert cont["load"]["first_preview_s"] is not None
    assert artifact["whole_batch"]["metrics"]["step_batching"] is None


# --------------------------------------------------------------------------
# real tiny pipeline: the step path is bit-identical to monolithic
# --------------------------------------------------------------------------


def _step_drive_bit_identity(pipe, steps=3):
    """Drive one real pipeline's step contract through the canonical
    interleaving (solo monolithic vs solo step vs joined vs preempted-
    and-resumed) and assert byte equality — the tentpole correctness
    bar, exercising the family's stepwise_carry_* runner hooks."""
    from distrifuser_tpu.serve.executors import PipelineExecutor

    pipe.set_stepwise(True)  # what apply_key_policy does for step keys
    ex = PipelineExecutor(pipe, steps=steps)
    mono = np.asarray(ex(["a cat"], [""], 5.0, [7])[0])

    # joined + preempted interleaving
    wa = ex.step_begin("a cat", "", 7, 5.0)
    ex.step_run([wa])                        # a: 1
    wb = ex.step_begin("a dog", "", 9, 5.0)  # joins mid-flight
    ex.step_run([wa, wb])                    # a: 2, b: 1
    ex.step_park(wa)                         # preempt a
    ex.step_run([wb])                        # b: 2
    ex.step_resume(wa)
    ex.step_run([wa, wb])                    # a: 3 done, b: 3 done
    assert ex.step_done(wa) and ex.step_done(wb)
    img_a = np.asarray(ex.step_finish(wa))
    img_b = np.asarray(ex.step_finish(wb))

    # solo step-granular references
    wc = ex.step_begin("a dog", "", 9, 5.0)
    for _ in range(steps):
        ex.step_run([wc])
    img_c = np.asarray(ex.step_finish(wc))

    np.testing.assert_array_equal(mono, img_a)
    np.testing.assert_array_equal(img_b, img_c)
    mono_b = np.asarray(ex(["a dog"], [""], 5.0, [9])[0])
    np.testing.assert_array_equal(mono_b, img_b)

    # previews are cheap host work with a bounded size
    wd = ex.step_begin("a cat", "", 7, 5.0)
    ex.step_run([wd])
    pv = ex.step_preview(wd, 8)
    assert pv.shape[0] <= 8 and pv.shape[1] <= 8 and pv.shape[2] == 3
    assert pv.dtype == np.float32
    ex.step_abort(wd)


def test_real_pipeline_step_bit_identity(devices8):
    """UNet/SD family: the canonical step-contract drive on the real
    tiny config (the carry threads (x, pstate, sstate) through
    DenoiseRunner's per-step programs)."""
    from test_pipelines import build_sd_pipeline

    pipe, _ = build_sd_pipeline(devices8, 1, batch_size=2)
    _step_drive_bit_identity(pipe)


def test_real_pipeline_step_bit_identity_dit(devices8):
    """DiT/PixArt family: the same drive through DiTDenoiseRunner's
    stepwise_carry hooks — its (x, sstate, kv) carry and
    _exec_phases-based phase math must match _generate_stepwise
    exactly, or joined runs drift from solo ones."""
    from test_staging import build_pixart_pipeline

    pipe = build_pixart_pipeline(devices8, 1, batch_size=2)
    _step_drive_bit_identity(pipe)


@pytest.mark.slow
def test_real_pipeline_step_bit_identity_mmdit(devices8):
    """SD3/MMDiT family: the same drive through MMDiTDenoiseRunner's
    stepwise_carry hooks (_exec_window-based sync flag).  Slow-marked:
    the tiny SD3 stack is the heaviest of the three compiles and the
    DiT test already covers the shared kv-carry shape on the 2-core
    runner."""
    from test_sd3_pipeline import build_sd3_pipeline

    pipe, _ = build_sd3_pipeline(devices8, 1, batch_size=2)
    _step_drive_bit_identity(pipe)


# --------------------------------------------------------------------------
# fused cohort dispatch: rowpack carry-layout unit behaviour
# --------------------------------------------------------------------------


def test_rowpack_axes_pack_extract_roundtrip():
    import jax
    import jax.numpy as jnp

    from distrifuser_tpu.parallel import rowpack

    def carry(seed, w):
        # the four leaf species a real carry mixes: a plain batch-axis
        # leaf, a CFG-folded fold-major/batch-minor leaf (2w rows, the
        # request row minor), a per-run scheduler scalar, and a
        # batch-less shared placeholder.  A SOLO carry's rows are
        # identical copies of its one real row (the _pad_batch
        # convention) — pack members arrive already at the compiled
        # width
        row = np.arange(6, dtype=np.float32) + seed
        base = np.tile(row[None], (w, 1))
        folded = np.concatenate([base, base + 100.0], axis=0)
        return {"x": jnp.asarray(base), "folded": jnp.asarray(folded),
                "ctr": jnp.asarray(float(seed)),
                "shared": jnp.ones((3,), jnp.float32)}

    axes = rowpack.axes_from_shapes(carry(0, 1), carry(0, 2))
    # tree_leaves order for a dict is sorted keys: ctr, folded, shared, x
    assert [a.axis for a in axes] == [None, 0, None, 0]
    assert axes[0].ndim == 0 and axes[2].ndim == 1

    width = 2
    a, b = carry(1, width), carry(2, width)
    packed = rowpack.pack_rows([a, b], [0, 0], axes, width)
    assert packed["x"].shape == (2, 6)
    np.testing.assert_array_equal(np.asarray(packed["ctr"]), [1.0, 2.0])
    # fold-major/batch-minor: a's fold blocks land at rows {0, 2}, b's
    # at {1, 3}
    np.testing.assert_array_equal(np.asarray(packed["folded"][0]),
                                  np.asarray(a["folded"][0]))
    np.testing.assert_array_equal(np.asarray(packed["folded"][1]),
                                  np.asarray(b["folded"][0]))
    np.testing.assert_array_equal(np.asarray(packed["folded"][2]),
                                  np.asarray(a["folded"][2]))

    # extract reproduces the solo layout byte-exactly (solo rows are
    # identical by construction, so tile(row) == the never-packed carry)
    for w_solo, row in ((a, 0), (b, 1)):
        solo = rowpack.extract_row(packed, row, axes, width)
        for k in w_solo:
            np.testing.assert_array_equal(np.asarray(solo[k]),
                                          np.asarray(w_solo[k]))

    # padding repeats the last member; extract of the real row is intact
    short = rowpack.pack_rows([a], [0], axes, width)
    solo = rowpack.extract_row(short, 0, axes, width)
    for k in a:
        np.testing.assert_array_equal(np.asarray(solo[k]),
                                      np.asarray(a[k]))

    # a previously-packed member contributes its own row
    repacked = rowpack.pack_rows([packed, a], [1, 0], axes, width)
    solo_b = rowpack.extract_row(repacked, 0, axes, width)
    for k in b:
        np.testing.assert_array_equal(np.asarray(solo_b[k]),
                                      np.asarray(b[k]))

    # donation safety: shared leaves are COPIED, never aliased, in both
    # directions (the per-step programs donate carry buffers)
    assert packed["shared"] is not a["shared"]
    assert (packed["shared"].unsafe_buffer_pointer()
            != a["shared"].unsafe_buffer_pointer())
    extracted = rowpack.extract_row(packed, 0, axes, width)
    assert (extracted["shared"].unsafe_buffer_pointer()
            != packed["shared"].unsafe_buffer_pointer())


def test_rowpack_ambiguity_rejects():
    import jax.numpy as jnp

    from distrifuser_tpu.parallel import rowpack

    # two axes double together -> no unique batch axis
    with pytest.raises(rowpack.AmbiguousPackAxisError, match="multiple"):
        rowpack.axes_from_shapes({"h": jnp.zeros((1, 1, 4))},
                                 {"h": jnp.zeros((2, 2, 4))})
    # rank change with width -> structure is width-dependent
    with pytest.raises(rowpack.AmbiguousPackAxisError, match="rank"):
        rowpack.axes_from_shapes({"h": jnp.zeros((1, 4))},
                                 {"h": jnp.zeros((2, 4, 1))})
    # mismatched member treedefs reject instead of mis-zipping leaves
    axes = rowpack.axes_from_shapes({"h": jnp.zeros((1, 4))},
                                    {"h": jnp.zeros((2, 4))})
    with pytest.raises(rowpack.AmbiguousPackAxisError, match="structure"):
        rowpack.pack_rows([{"h": jnp.zeros((2, 4))},
                           {"g": jnp.zeros((2, 4))}], [0, 0], axes, 2)
    # a batch axis that does not divide the width cannot be fold-indexed
    with pytest.raises(rowpack.AmbiguousPackAxisError, match="multiple of"):
        rowpack.extract_row({"h": jnp.zeros((3, 4))}, 0, axes, 2)


# --------------------------------------------------------------------------
# fused cohort dispatch: pack-aligned cohort selection (fakes)
# --------------------------------------------------------------------------


def _sigged_states(sb, sigs_ttls, now):
    states = []
    for sig, ttl in sigs_ttls:
        st = mk_state(mk_request(ttl=ttl, now=now))
        st.work = {"sig": sig}
        sb.admit(st)
        states.append(st)
    return states


def test_cohort_pack_align_fills_with_matching_signature():
    sb = StepBatcher(step_config(slots=6, step_width=2, pack_align=True),
                     clock=time.monotonic,
                     pack_signature=lambda s: s.work.get("sig"))
    now = time.monotonic()
    # EDF order by ttl: A(1) B(2) A(3) B(4); width 2 -> the anchor A plus
    # the NEXT A, skipping the tighter B (which still outranks next round)
    states = _sigged_states(
        sb, [("A", 1.0), ("B", 2.0), ("A", 3.0), ("B", 4.0)], now)
    cohort = sb.cohort(now)
    assert cohort == [states[0], states[2]]
    assert sb.pack_aligned == 1
    assert sb.snapshot()["pack_aligned"] == 1
    # anchor with a signature nobody shares falls back to plain EDF width
    for st in states:
        sb.remove(st)
    states = _sigged_states(
        sb, [("C", 1.0), ("B", 2.0), ("B", 3.0)], now)
    assert sb.cohort(now) == [states[0], states[1]]


def test_cohort_pack_align_off_or_unsigned_is_plain_edf():
    # pack_align=False -> plain EDF truncation even with matching sigs
    sb = StepBatcher(step_config(slots=6, step_width=2, pack_align=False),
                     clock=time.monotonic,
                     pack_signature=lambda s: s.work.get("sig"))
    now = time.monotonic()
    states = _sigged_states(
        sb, [("A", 1.0), ("B", 2.0), ("A", 3.0)], now)
    assert sb.cohort(now) == [states[0], states[1]]
    assert sb.pack_aligned == 0
    # no signature source (executor without step_signature) -> plain EDF
    sb2 = StepBatcher(step_config(slots=6, step_width=2, pack_align=True),
                      clock=time.monotonic)
    states2 = _sigged_states(
        sb2, [("A", 1.0), ("B", 2.0), ("A", 3.0)], now)
    assert sb2.cohort(now) == [states2[0], states2[1]]
    # a signature source that raises is treated as unsigned, not fatal
    def boom(_state):
        raise RuntimeError("no signature for you")
    sb3 = StepBatcher(step_config(slots=6, step_width=2, pack_align=True),
                      clock=time.monotonic, pack_signature=boom)
    states3 = _sigged_states(
        sb3, [("A", 1.0), ("B", 2.0), ("A", 3.0)], now)
    assert sb3.cohort(now) == [states3[0], states3[1]]


def test_server_counts_packed_dispatches_on_fakes():
    """The fakes report pack stats (one dispatch per cohort round), and
    the server folds them into the stepbatch_dispatches /
    stepbatch_packed_rows counters and the pack-fill gauge."""
    fac = StepFakeExecutorFactory(batch_size=4, step_time_s=0.002)
    with InferenceServer(fac, serve_config()) as server:
        futs = [server.submit(f"p{i}", height=64, width=64, seed=i)
                for i in range(3)]
        for f in futs:
            f.result(timeout=30)
        snap = server.metrics_snapshot()
    reqs = snap["requests"]
    assert reqs["stepbatch_dispatches"] >= 1
    assert reqs["stepbatch_packed_rows"] >= reqs["stepbatch_dispatches"]
    # every fake round is one dispatch, so total packed rows equals total
    # member-steps executed
    assert reqs["stepbatch_packed_rows"] == reqs["steps_executed"]


# --------------------------------------------------------------------------
# fused cohort dispatch: real tiny pipelines pack bit-identically
# --------------------------------------------------------------------------


def test_real_pipeline_packed_dispatch_and_migration(devices8):
    """The tentpole on the real tiny SD config: two same-signature works
    advance in ONE compiled dispatch (step_pack_stats proves packing
    engaged, not a silent sequential fallback), the repeat round takes
    the zero-repack fast path, a packed member migrates out via
    step_export into a fresh executor, and every image is byte-equal to
    its solo run."""
    from test_pipelines import build_sd_pipeline

    from distrifuser_tpu.serve.executors import PipelineExecutor

    steps = 3
    pipe, _ = build_sd_pipeline(devices8, 1, batch_size=2)
    pipe.set_stepwise(True)
    ex = PipelineExecutor(pipe, steps=steps)

    def solo_image(prompt, seed):
        w = ex.step_begin(prompt, "", seed, 5.0)
        for _ in range(steps):
            ex.step_run([w])
            assert ex.step_pack_stats["dispatches"] == 1
        return np.asarray(ex.step_finish(w))

    ref_cat = solo_image("a cat", 7)
    ref_dog = solo_image("a dog", 9)

    wa = ex.step_begin("a cat", "", 7, 5.0)
    wb = ex.step_begin("a dog", "", 9, 5.0)
    ex.step_run([wa, wb])
    # ONE dispatch carried both members' rows
    assert ex.step_pack_stats == {"dispatches": 1, "packed_rows": 2,
                                  "rows_capacity": 2}
    assert wa["carry"] is wb["carry"]
    assert sorted([wa["row"], wb["row"]]) == [0, 1]

    # steady state: same group, same carry -> the fast path re-dispatches
    # with zero repack work (still one dispatch)
    ex.step_run([wa, wb])
    assert ex.step_pack_stats == {"dispatches": 1, "packed_rows": 2,
                                  "rows_capacity": 2}
    assert wa["carry"] is wb["carry"]

    # migration across a packed round: export the packed member (the
    # snapshot is the SOLO layout, identical to a never-packed export),
    # graft it into a fresh executor, and finish there
    meta, leaves = ex.step_export(wb)
    assert meta["step"] == 2 and wb.get("pack") is None
    ex.step_abort(wb)
    ex2 = PipelineExecutor(pipe, steps=steps)
    wb2 = ex2.step_import(meta, leaves, "a dog", "", 9, 5.0)
    while not ex2.step_done(wb2):
        ex2.step_run([wb2])
    np.testing.assert_array_equal(ref_dog, np.asarray(ex2.step_finish(wb2)))

    # the member left behind finishes solo, byte-equal
    ex.step_run([wa])
    np.testing.assert_array_equal(ref_cat, np.asarray(ex.step_finish(wa)))


def test_real_pipeline_preempt_mid_packed_round(devices8):
    """Preempt-vs-pack: park a member of an ACTIVE pack (its carry is
    shared with the survivor), let the survivor run ahead solo, resume,
    and re-pack at DIFFERENT step indices — the per-row step-index
    vector is exactly what makes that one dispatch.  Both images stay
    byte-equal to solo runs."""
    from test_pipelines import build_sd_pipeline

    from distrifuser_tpu.serve.executors import PipelineExecutor

    steps = 4
    pipe, _ = build_sd_pipeline(devices8, 1, batch_size=2)
    pipe.set_stepwise(True)
    ex = PipelineExecutor(pipe, steps=steps)

    def solo_image(prompt, seed):
        w = ex.step_begin(prompt, "", seed, 5.0)
        for _ in range(steps):
            ex.step_run([w])
        return np.asarray(ex.step_finish(w))

    ref_cat = solo_image("a cat", 7)
    ref_dog = solo_image("a dog", 9)

    we = ex.step_begin("a cat", "", 7, 5.0)
    wf = ex.step_begin("a dog", "", 9, 5.0)
    ex.step_run([we, wf])                    # packed: e:1 f:1
    assert ex.step_pack_stats["dispatches"] == 1
    ex.step_park(we)                         # unpacks e out of the pack
    assert we.get("pack") is None
    ex.step_run([wf])                        # f:2 (solo, repacked away)
    ex.step_resume(we)
    ex.step_run([we, wf])                    # e:1->2, f:2->3 in ONE call
    assert ex.step_pack_stats == {"dispatches": 1, "packed_rows": 2,
                                  "rows_capacity": 2}
    assert we["i"] == 2 and wf["i"] == 3
    ex.step_run([we, wf])                    # e:3, f:4 done
    ex.step_run([we])                        # e:4 done
    np.testing.assert_array_equal(ref_dog, np.asarray(ex.step_finish(wf)))
    np.testing.assert_array_equal(ref_cat, np.asarray(ex.step_finish(we)))


def test_real_pipeline_mixed_signature_groups(devices8):
    """A cohort mixing compiled-step signatures splits into per-
    signature groups: same-signature members share one dispatch, the
    odd one out dispatches alone, and nothing packs ACROSS signatures.
    The 4-device config is 2-way SP patch parallelism (CFG split takes
    the other mesh factor), so with warmup_steps=1 step 2 runs the
    STALE displaced-patch program while steps 0-1 run SYNC — a real
    warmup-vs-stale signature mix, with the displaced-patch state dict
    riding the packed carry.  Results stay byte-equal to solo runs."""
    from test_pipelines import build_sd_pipeline

    from distrifuser_tpu.serve.executors import PipelineExecutor

    steps = 3
    pipe, _ = build_sd_pipeline(devices8, 4, batch_size=2)
    assert pipe.distri_config.is_sp  # the premise: phases really differ
    pipe.set_stepwise(True)
    ex = PipelineExecutor(pipe, steps=steps)

    def solo_image(prompt, seed):
        w = ex.step_begin(prompt, "", seed, 5.0)
        for _ in range(steps):
            ex.step_run([w])
        return np.asarray(ex.step_finish(w))

    refs = [solo_image(p, s) for p, s in
            (("a cat", 7), ("a dog", 9), ("a fox", 11))]

    wa = ex.step_begin("a cat", "", 7, 5.0)
    ex.step_run([wa])
    ex.step_run([wa])                        # a:2 — next step is STALE
    wb = ex.step_begin("a dog", "", 9, 5.0)
    wc = ex.step_begin("a fox", "", 11, 5.0)
    siga = ex.step_signature(wa)
    sigb = ex.step_signature(wb)
    assert siga is not None and sigb is not None and siga != sigb
    assert sigb == ex.step_signature(wc)
    ex.step_run([wa, wb, wc])                # a:3 done, b:1, c:1
    stats = ex.step_pack_stats
    # b+c share the warmup signature (one dispatch); a dispatches alone
    assert stats["dispatches"] == 2 and stats["packed_rows"] == 3
    assert wb["carry"] is wc["carry"] and wa["carry"] is not wb["carry"]
    img_a = np.asarray(ex.step_finish(wa))
    ex.step_run([wb, wc])                    # b:2, c:2 — sync+state pack
    assert ex.step_pack_stats["dispatches"] == 1
    ex.step_run([wb, wc])                    # b:3, c:3 — stale pack
    assert ex.step_pack_stats["dispatches"] == 1
    np.testing.assert_array_equal(refs[0], img_a)
    np.testing.assert_array_equal(refs[1], np.asarray(ex.step_finish(wb)))
    np.testing.assert_array_equal(refs[2], np.asarray(ex.step_finish(wc)))

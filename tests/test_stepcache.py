"""Temporal step-cache (parallel/stepcache.py): cadence math, parity of the
full/shallow loop against the cache-off loop on all three model families,
fused-vs-stepwise equivalence, the per-phase comm/FLOP report, the serve
surfaces, and (slow) the HLO proof that skipped layers' refresh collectives
vanish from the shallow body."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distrifuser_tpu import DistriConfig
from distrifuser_tpu.models import dit as dit_mod
from distrifuser_tpu.models import mmdit as mm
from distrifuser_tpu.models.unet import init_unet_params, tiny_config
from distrifuser_tpu.parallel import stepcache
from distrifuser_tpu.parallel.dit_sp import DiTDenoiseRunner
from distrifuser_tpu.parallel.mmdit_sp import MMDiTDenoiseRunner
from distrifuser_tpu.parallel.runner import DenoiseRunner
from distrifuser_tpu.schedulers import get_scheduler


# ---------------------------------------------------------------------------
# cadence math
# ---------------------------------------------------------------------------


def test_cadence_math():
    assert stepcache.cadence_split(8, 2) == (4, 0)
    assert stepcache.cadence_split(7, 3) == (2, 1)
    assert stepcache.cadence_split(0, 2) == (0, 0)
    # shallow-first: positions 0..I-2 shallow, I-1 full
    assert [stepcache.is_shallow_step(k, 2) for k in range(4)] == [
        True, False, True, False]
    assert [stepcache.is_shallow_step(k, 3) for k in range(6)] == [
        True, True, False, True, True, False]
    # 10 steps, warmup 1 -> 2 sync, 8 cadenced at interval 2 -> 4 shallow
    assert stepcache.shallow_step_count(10, 1, 2) == 4
    # tail steps stay shallow: 7 cadenced at interval 3 -> 5 shallow
    assert stepcache.shallow_step_count(9, 1, 3) == 5
    assert stepcache.shallow_step_count(10, 1, 1) == 0  # cache off
    assert stepcache.shallow_step_count(2, 4, 2) == 0  # never leaves warmup


def test_config_validation():
    kw = dict(devices=jax.devices()[:1], height=128, width=128)
    with pytest.raises(ValueError, match="BOTH knobs"):
        DistriConfig(step_cache_interval=2, **kw)
    with pytest.raises(ValueError, match="BOTH knobs"):
        DistriConfig(step_cache_depth=1, **kw)
    with pytest.raises(ValueError, match="hybrid_loop"):
        DistriConfig(step_cache_interval=2, step_cache_depth=1,
                     hybrid_loop=True, **kw)
    with pytest.raises(ValueError, match="parallelism"):
        DistriConfig(step_cache_interval=2, step_cache_depth=1,
                     parallelism="naive_patch", **kw)
    # runner-level depth bound: tiny UNet has 2 levels -> depth must be 1
    cfg = DistriConfig(step_cache_interval=2, step_cache_depth=2, **kw)
    ucfg = tiny_config()
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    with pytest.raises(ValueError, match="step_cache_depth"):
        DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
    # DiT depth bound (tiny DiT has 8 blocks)
    dcfg = dit_mod.tiny_dit_config()
    cfg_d = DistriConfig(step_cache_interval=2, step_cache_depth=8,
                         devices=jax.devices()[:1],
                         height=dcfg.sample_size * 8,
                         width=dcfg.sample_size * 8)
    dparams = dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg)
    with pytest.raises(ValueError, match="step_cache_depth"):
        DiTDenoiseRunner(cfg_d, dcfg, dparams, get_scheduler("ddim"))
    # MMDiT: the cut must stay past the dual-attention prefix
    mcfg = dataclasses.replace(mm.tiny_mmdit_config(),
                               dual_attention_blocks=3)
    mparams = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)
    cfg_m = DistriConfig(step_cache_interval=2, step_cache_depth=2,
                         devices=jax.devices()[:1],
                         height=mcfg.sample_size * 8,
                         width=mcfg.sample_size * 8)
    with pytest.raises(ValueError, match="dual"):
        MMDiTDenoiseRunner(cfg_m, mcfg, mparams,
                           get_scheduler("flow-euler"))


# ---------------------------------------------------------------------------
# UNet parity (single device keeps the tier-1 compile budget small; the
# multi-device displaced variants run in the slow block below)
# ---------------------------------------------------------------------------


def _unet_runner(devices, n, **kw):
    cfg = DistriConfig(devices=devices[:n], height=128, width=128,
                       warmup_steps=1, parallelism="patch", **kw)
    ucfg = tiny_config(sdxl=False)
    params = init_unet_params(jax.random.PRNGKey(0), ucfg)
    return DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim")), cfg, ucfg


def _unet_inputs(cfg, ucfg):
    k = jax.random.PRNGKey(42)
    lat = jax.random.normal(
        k, (1, cfg.latent_height, cfg.latent_width, ucfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 1, 7, ucfg.cross_attention_dim))
    return lat, enc


def test_unet_parity_single_device():
    devs = jax.devices()
    r_off, cfg, ucfg = _unet_runner(devs, 1)
    r_on, _, _ = _unet_runner(devs, 1, step_cache_interval=2,
                              step_cache_depth=1)
    lat, enc = _unet_inputs(cfg, ucfg)
    # a run that never leaves warmup is bit-identical: every step is full
    a2 = np.asarray(r_off.generate(lat, enc, num_inference_steps=2))
    b2 = np.asarray(r_on.generate(lat, enc, num_inference_steps=2))
    np.testing.assert_array_equal(a2, b2)
    # cadenced run stays within tolerance of cache-off (measured ~0.03
    # relative on this config; 0.15 leaves platform margin while still far
    # below the 0.35 displaced-mode gate in test_runner.py)
    a6 = np.asarray(r_off.generate(lat, enc, num_inference_steps=6))
    b6 = np.asarray(r_on.generate(lat, enc, num_inference_steps=6))
    assert np.isfinite(b6).all()
    rel = np.abs(a6 - b6).max() / (np.abs(a6).max() + 1e-6)
    assert rel < 0.15, f"step-cache drift {rel}"
    assert rel > 0, "cache-on unexpectedly bit-identical: shallow steps dead?"
    # the host-driven stepwise loop replays the exact cadence
    r_sw, _, _ = _unet_runner(devs, 1, step_cache_interval=2,
                              step_cache_depth=1, use_cuda_graph=False)
    c6 = np.asarray(r_sw.generate(lat, enc, num_inference_steps=6))
    np.testing.assert_allclose(b6, c6, atol=2e-4)


def test_unet_tail_and_callback():
    """interval 3 with a non-multiple step count exercises the unrolled
    shallow tail; the callback path must fire per executed step and match
    the fused cadence numerics."""
    devs = jax.devices()
    r_on, cfg, ucfg = _unet_runner(devs, 1, step_cache_interval=3,
                                   step_cache_depth=1)
    r_sw, _, _ = _unet_runner(devs, 1, step_cache_interval=3,
                              step_cache_depth=1, use_cuda_graph=False)
    lat, enc = _unet_inputs(cfg, ucfg)
    a = np.asarray(r_on.generate(lat, enc, num_inference_steps=7))
    b = np.asarray(r_sw.generate(lat, enc, num_inference_steps=7))
    np.testing.assert_allclose(a, b, atol=2e-4)
    seen = []
    out = r_on.generate(lat, enc, num_inference_steps=4,
                        callback=lambda i, t, x: seen.append(i))
    assert seen == [0, 1, 2, 3]
    assert np.isfinite(np.asarray(out)).all()


def test_unet_per_phase_report_and_flops():
    devs = jax.devices()
    r_on, _, _ = _unet_runner(devs, 1, step_cache_interval=2,
                              step_cache_depth=1)
    rep = r_on.comm_volume_report(per_phase=True)
    # single device: the only carried state is the deep cache itself, and a
    # shallow step freshly exchanges nothing
    assert rep["phases"]["sync"] == {"stepcache": 32768}
    assert rep["phases"]["shallow"] == {}
    fl = rep["flops"]
    assert fl is not None and 0 < fl["shallow_ratio"] < 0.7, fl
    # cache off: legacy report shape is untouched, per-phase flops absent
    r_off, _, _ = _unet_runner(devs, 1)
    assert r_off.comm_volume_report() == {}
    assert r_off.comm_volume_report(per_phase=True)["flops"] is None


# ---------------------------------------------------------------------------
# DiT / MMDiT parity (deep-block residual cache)
# ---------------------------------------------------------------------------


def _dit_runner(n, dcfg, params, **kw):
    cfg = DistriConfig(devices=jax.devices()[:n], height=dcfg.sample_size * 8,
                       width=dcfg.sample_size * 8, warmup_steps=1, **kw)
    return DiTDenoiseRunner(cfg, dcfg, params, get_scheduler("ddim"))


def test_dit_parity_single_device():
    dcfg = dit_mod.tiny_dit_config()
    params = dit_mod.init_dit_params(jax.random.PRNGKey(0), dcfg)
    k = jax.random.PRNGKey(3)
    lat = jax.random.normal(
        k, (1, dcfg.sample_size, dcfg.sample_size, dcfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 1, 8, dcfg.caption_dim))
    r_off = _dit_runner(1, dcfg, params)
    r_on = _dit_runner(1, dcfg, params, step_cache_interval=2,
                       step_cache_depth=4)
    a2 = np.asarray(r_off.generate(lat, enc, num_inference_steps=2))
    b2 = np.asarray(r_on.generate(lat, enc, num_inference_steps=2))
    np.testing.assert_array_equal(a2, b2)  # warmup-only: bit-identical
    a6 = np.asarray(r_off.generate(lat, enc, num_inference_steps=6))
    b6 = np.asarray(r_on.generate(lat, enc, num_inference_steps=6))
    assert np.isfinite(b6).all()
    rel = np.abs(a6 - b6).max() / (np.abs(a6).max() + 1e-6)
    assert 0 < rel < 0.05, f"DiT step-cache drift {rel}"
    r_sw = _dit_runner(1, dcfg, params, step_cache_interval=2,
                       step_cache_depth=4, use_cuda_graph=False)
    c6 = np.asarray(r_sw.generate(lat, enc, num_inference_steps=6))
    np.testing.assert_allclose(b6, c6, atol=2e-4)
    rep = r_on.comm_report()
    assert rep["step_cache"]["interval"] == 2


def test_mmdit_parity_single_device():
    mcfg = mm.tiny_mmdit_config()
    params = mm.init_mmdit_params(jax.random.PRNGKey(0), mcfg)
    k = jax.random.PRNGKey(7)
    lat = jax.random.normal(
        k, (1, mcfg.sample_size, mcfg.sample_size, mcfg.in_channels))
    enc = jax.random.normal(
        jax.random.fold_in(k, 1), (2, 1, 5, mcfg.joint_attention_dim))
    pooled = jax.random.normal(
        jax.random.fold_in(k, 2), (2, 1, mcfg.pooled_projection_dim))

    def mk(**kw):
        cfg = DistriConfig(devices=jax.devices()[:1],
                           height=mcfg.sample_size * 8,
                           width=mcfg.sample_size * 8, warmup_steps=1, **kw)
        return MMDiTDenoiseRunner(cfg, mcfg, params,
                                  get_scheduler("flow-euler"))

    r_off, r_on = mk(), mk(step_cache_interval=2, step_cache_depth=1)
    a2 = np.asarray(r_off.generate(lat, enc, pooled, num_inference_steps=2))
    b2 = np.asarray(r_on.generate(lat, enc, pooled, num_inference_steps=2))
    np.testing.assert_array_equal(a2, b2)
    a6 = np.asarray(r_off.generate(lat, enc, pooled, num_inference_steps=6))
    b6 = np.asarray(r_on.generate(lat, enc, pooled, num_inference_steps=6))
    assert np.isfinite(b6).all()
    rel = np.abs(a6 - b6).max() / (np.abs(a6).max() + 1e-6)
    assert 0 < rel < 0.05, f"MMDiT step-cache drift {rel}"
    r_sw = mk(step_cache_interval=2, step_cache_depth=1,
              use_cuda_graph=False)
    c6 = np.asarray(r_sw.generate(lat, enc, pooled, num_inference_steps=6))
    np.testing.assert_allclose(b6, c6, atol=2e-4)


# ---------------------------------------------------------------------------
# serve surfaces
# ---------------------------------------------------------------------------


def test_serve_exec_key_and_metrics():
    from distrifuser_tpu.serve.cache import ExecKey
    from distrifuser_tpu.serve.server import InferenceServer
    from distrifuser_tpu.serve.testing import FakeExecutorFactory
    from distrifuser_tpu.utils.config import ServeConfig

    base = dict(model_id="m", scheduler="ddim", height=512, width=512,
                steps=8, cfg=True, mesh_plan="dp1.cfg1.sp1")
    k_off = ExecKey(**base)
    k_on = ExecKey(**base, step_cache_interval=2, step_cache_depth=1)
    # two requests differing only in cadence must not share an executor
    assert k_off != k_on
    assert ":sc2x1" in k_on.short() and ":sc" not in k_off.short()

    with pytest.raises(ValueError, match="BOTH knobs"):
        ServeConfig(step_cache_interval=2)

    fac = FakeExecutorFactory(batch_size=4)
    cfg = ServeConfig(step_cache_interval=2, step_cache_depth=1,
                      batch_window_s=0.0)
    srv = InferenceServer(fac, cfg, model_id="m").start(warmup=False)
    try:
        futs = [srv.submit(f"p{i}", height=512, width=512,
                           num_inference_steps=9, seed=i) for i in range(3)]
        for f in futs:
            f.result(timeout=10)
        snap = srv.metrics_snapshot()
    finally:
        srv.stop()
    sc = snap["step_cache"]
    assert sc["interval"] == 2 and sc["steps_total"] == 27
    # fake executors model warmup 0: 9 steps -> 4 shallow each
    assert sc["steps_shallow"] == 12
    assert 0 < sc["shallow_share"] < 1
    assert ":sc2x1" in snap["cache"]["entries"][0]


def test_pipeline_step_cache_plan(devices8):
    from test_pipelines import build_sd_pipeline

    pipe, _ = build_sd_pipeline(devices8, 1, step_cache_interval=2,
                                step_cache_depth=1, warmup_steps=1)
    plan = pipe.step_cache_plan(10)
    assert plan == {"enabled": True, "interval": 2, "depth": 1,
                    "total_steps": 10, "shallow_steps": 4}
    pipe_off, _ = build_sd_pipeline(devices8, 1)
    assert pipe_off.step_cache_plan(10)["shallow_steps"] == 0


# ---------------------------------------------------------------------------
# HLO: the shallow body drops the skipped layers' refresh collectives
# (8-device compiles: minutes on the tier-1 CPU runner -> slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_hlo_shallow_body_drops_refresh_collectives(devices8):
    """The compiled cache-on program (interval 2; 6 steps so the super-step
    scan has length 2 and survives as a while loop) carries the shallow
    steps as their own nested loop body (stepcache.run_cadence's inner
    fori), so the shallow body is directly inspectable.  With
    mode=separate_gn the only all-gathers are self-attention KV refreshes
    plus the per-step output gather, and the tiny config's attention all
    lives in the deep subtree — so:

    * the SHALLOW body must contain NO all-gather refresh at all (every
      skipped layer's KV gather vanished) and strictly fewer halo permutes
      than a full step (only shallow convs still displace);
    * the FULL (super-step) body must match the cache-off stale body's
      deferred refresh set exactly — the cache changes what shallow steps
      skip, not what full steps exchange."""
    from distrifuser_tpu.models import unet as unet_mod
    from distrifuser_tpu.utils.overlap import analyze_loop_collectives

    ucfg = unet_mod.tiny_config(sdxl=False)
    params = unet_mod.init_unet_params(jax.random.PRNGKey(0), ucfg)
    depth = len(ucfg.block_out_channels) - 1

    def hlo(**kw):
        cfg = DistriConfig(
            devices=devices8, height=8 * 8 * (1 << depth) * 2, width=128,
            warmup_steps=1, parallelism="patch", mode="separate_gn", **kw,
        )
        runner = DenoiseRunner(cfg, ucfg, params, get_scheduler("ddim"))
        lat = jnp.zeros(
            (1, cfg.latent_height, cfg.latent_width, ucfg.in_channels))
        enc = jnp.zeros((2, 1, 7, ucfg.cross_attention_dim))
        fn = runner._build(6)
        return fn.lower(params, lat, enc, None, 5.0).compile().as_text()

    def count(report, prefix, which="deferred"):
        return sum(1 for op in getattr(report, which).values()
                   if op.startswith(prefix))

    off_reports = analyze_loop_collectives(hlo())
    assert off_reports, "no while-loop collectives found"
    off = max(off_reports, key=lambda r: r.n_deferred)
    assert count(off, "all-gather") > 0 and count(off, "collective-permute"), (
        off.deferred, "analysis lost signal")

    on_reports = [r for r in analyze_loop_collectives(
        hlo(step_cache_interval=2, step_cache_depth=1)) if r.n_deferred]
    assert len(on_reports) == 2, [
        (r.body, r.deferred, r.inline) for r in on_reports]
    full = max(on_reports, key=lambda r: r.n_deferred)
    shallow = min(on_reports, key=lambda r: r.n_deferred)
    # full steps exchange exactly what cache-off steps exchange
    for prefix in ("all-gather", "collective-permute"):
        assert count(full, prefix) == count(off, prefix), prefix
    # the shallow body: zero KV refresh gathers anywhere, and strictly
    # fewer halo permutes than a full step (deep convs' permutes gone)
    assert count(shallow, "all-gather") == 0, shallow.deferred
    assert 0 < count(shallow, "collective-permute") < count(
        off, "collective-permute"), (shallow.deferred, off.deferred)
    # its only inline collective work is the per-step output gather
    assert set(shallow.inline.values()) <= {"all-gather"}, shallow.inline


@pytest.mark.slow
def test_unet_multi_device_parity(devices8):
    """Displaced 8-device (cfg 2 x sp 4) cadence: cache-on tracks cache-off
    and the stepwise loop replays the fused program exactly."""
    r_off, cfg, ucfg = _unet_runner(devices8, 8)
    r_on, _, _ = _unet_runner(devices8, 8, step_cache_interval=2,
                              step_cache_depth=1)
    r_sw, _, _ = _unet_runner(devices8, 8, step_cache_interval=2,
                              step_cache_depth=1, use_cuda_graph=False)
    lat, enc = _unet_inputs(cfg, ucfg)
    a = np.asarray(r_off.generate(lat, enc, num_inference_steps=6))
    b = np.asarray(r_on.generate(lat, enc, num_inference_steps=6))
    c = np.asarray(r_sw.generate(lat, enc, num_inference_steps=6))
    assert np.isfinite(b).all()
    rel = np.abs(a - b).max() / (np.abs(a).max() + 1e-6)
    assert rel < 0.2, f"multi-device step-cache drift {rel}"
    np.testing.assert_allclose(b, c, atol=2e-4)
    # per-phase report on the real mesh: the shallow phase must freshly
    # exchange strictly less than the stale phase, and never any attn KV
    rep = r_on.comm_volume_report(per_phase=True)
    ph = rep["phases"]
    assert "attn" not in ph["shallow"]
    assert sum(ph["shallow"].values()) < sum(
        v for k, v in ph["stale"].items() if k != "stepcache")
